"""Batch/transformer sweep on the attached accelerator.

Runs the headline bench functions at alternative configs to find the
best-throughput operating points (the headline BENCH artifact keeps its
fixed config for round-over-round comparability; this sweep documents
where the ceiling is). One JSON line per config to stdout + appended to
the sweep artifact (`DL4J_SWEEP_OUT`, default repo-root SWEEP.jsonl —
`scripts/tunnel_window.sh` points it into the live-window capture dir).

Usage: python benchtools/bench_sweep.py [resnet|transformer|all]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import bench  # noqa: E402

OUT = os.environ.get(
    "DL4J_SWEEP_OUT",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "SWEEP.jsonl"))


def emit(tag, rec):
    rec = {"sweep": tag, **rec}
    # device_diagnostics repeats per record; keep the first only
    line = json.dumps(rec)
    print(line, flush=True)
    with open(OUT, "a") as f:
        f.write(line + "\n")


def sweep_resnet(accel):
    # batch sweep incl. the round-4 b256<b128 anomaly: vary steps at
    # b256 to separate working-set effects (the fused window stacks
    # steps x batch images on HBM) from per-step compute
    for batch, steps in ((64, 20), (128, 20), (192, 20), (256, 20),
                         (256, 10), (256, 5)):
        try:
            r = bench.bench_resnet50(accel, batch=batch, steps=steps,
                                     with_etl=False)
            r.pop("device_diagnostics", None)
            emit(f"resnet50_b{batch}_s{steps}", r)
        except Exception as e:
            emit(f"resnet50_b{batch}_s{steps}",
                 {"error": f"{type(e).__name__}: {e}"[:300]})


def sweep_transformer(accel):
    configs = [
        # (B, T, d_model, n_layers, n_heads) — the headline config first
        (16, 256, 256, 4, 8),
        (32, 512, 256, 4, 8),     # longer sequences, flash attn sweet spot
        (32, 512, 512, 8, 8),     # GPT-2-small-ish block shape
        (8, 2048, 512, 8, 8),     # long-context: flash attention tiling
    ]
    for B, T, d, L, H in configs:
        try:
            r = bench.bench_transformer_lm(accel, B=B, T=T, d_model=d,
                                           n_layers=L, n_heads=H)
            emit(f"transformer_B{B}_T{T}_d{d}_L{L}", r)
        except Exception as e:
            emit(f"transformer_B{B}_T{T}_d{d}_L{L}",
                 {"error": f"{type(e).__name__}: {e}"[:300]})


def main():
    what = sys.argv[1] if len(sys.argv) > 1 else "all"
    info = bench._probe_backend()
    if info is None:
        return
    plat, kind, accel, _ = info
    try:
        from deeplearning4j_tpu.nd import enable_compilation_cache
        enable_compilation_cache()
    except Exception:
        pass
    emit("env", {"platform": plat, "device_kind": kind,
                 "diagnostics": bench._device_diagnostics()})
    if what in ("resnet", "all"):
        sweep_resnet(accel)
    if what in ("transformer", "all"):
        sweep_transformer(accel)


if __name__ == "__main__":
    main()
