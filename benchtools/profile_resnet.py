"""One-shot on-chip profile of the ResNet-50 train step (VERDICT r4 #2:
"profile one train step on chip, commit the top-10 HLO cost table").

Runs the SAME AOT fused executable the headline bench times, under
`jax.profiler.trace`, then post-processes the captured xplane into a
per-op cost table (self-time aggregated by HLO category and by op
name), printed as JSON and written to PROFILE_r05/.

Usage: python benchtools/profile_resnet.py [batch] [steps]
(defaults 128 / 20 — the headline operating point).

Role match: `PerformanceListener.java:87-88` measurement tooling; the
xplane parse uses tensorflow's profiler proto (tensorflow ships in the
image as the keras backend — CPU-only, used here purely as a proto
reader).
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUTDIR = os.environ.get(
    "DL4J_PROFILE_OUT",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "PROFILE_live"))


def _xplane_proto():
    import importlib
    for mod in ("tensorflow.tsl.profiler.protobuf.xplane_pb2",
                "tensorflow.core.profiler.protobuf.xplane_pb2",
                "xprof.protobuf.xplane_pb2"):
        try:
            return importlib.import_module(mod)
        except ImportError:
            continue
    raise ImportError("no xplane_pb2 proto module found")


def parse_xplane(logdir):
    """Aggregate device-plane event self-times by event name."""
    xplane_pb2 = _xplane_proto()
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        return None
    totals = {}     # name -> duration ps
    device_total = 0
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            pname = plane.name.lower()
            if "tpu" not in pname and "device" not in pname and \
                    "/device:" not in pname and "xla" not in pname:
                continue
            ev_names = {k: v for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                for ev in line.events:
                    md = ev_names.get(ev.metadata_id)
                    name = md.name if md else str(ev.metadata_id)
                    dur = ev.duration_ps
                    totals[name] = totals.get(name, 0) + dur
                    device_total += dur
    return totals, device_total


def categorize(name: str) -> str:
    low = name.lower()
    for key, cat in (("convolution", "conv"), ("conv", "conv"),
                     ("dot", "matmul"), ("fusion", "fusion"),
                     ("reduce-window", "pooling"), ("reduce", "reduce"),
                     ("all-reduce", "collective"), ("copy", "copy"),
                     ("transpose", "transpose"), ("scatter", "scatter"),
                     ("dynamic", "dynamic-slice"), ("select", "select"),
                     ("broadcast", "broadcast"), ("infeed", "infeed"),
                     ("outfeed", "outfeed")):
        if key in low:
            return cat
    return "other"


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    os.makedirs(OUTDIR, exist_ok=True)

    from deeplearning4j_tpu import bench
    info = bench._probe_backend()
    if info is None:
        return
    plat, kind, accel, _ = info
    from deeplearning4j_tpu.nd import enable_compilation_cache
    enable_compilation_cache()

    import jax
    logdir = os.path.join(OUTDIR, f"trace_b{batch}")
    # run the headline bench once with the profiler wrapped around it —
    # the timed windows inside are exactly the fused executable
    with jax.profiler.trace(logdir):
        result = bench.bench_resnet50(accel, batch=batch, steps=steps,
                                      with_etl=False)
    parsed = parse_xplane(logdir)
    if parsed and not parsed[0]:
        parsed = None   # trace captured but no device plane (CPU run)
    report = {"bench": {k: result[k] for k in
                        ("value", "mfu", "achieved_tflops", "batch",
                         "seconds") if k in result}}
    if parsed:
        totals, device_total = parsed
        by_cat = {}
        for name, ps in totals.items():
            by_cat[categorize(name)] = by_cat.get(categorize(name), 0) + ps
        top_ops = sorted(totals.items(), key=lambda kv: -kv[1])[:25]
        report["device_total_ms"] = device_total / 1e9
        report["by_category_pct"] = {
            k: round(100.0 * v / max(device_total, 1), 2)
            for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1])}
        report["top_ops"] = [
            {"name": n[:120], "ms": round(ps / 1e9, 3),
             "pct": round(100.0 * ps / max(device_total, 1), 2)}
            for n, ps in top_ops]
    else:
        report["error"] = "no xplane captured (CPU backend or trace off)"
    out_path = os.path.join(OUTDIR, f"profile_b{batch}.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report)[:4000])
    print(f"\nwritten: {out_path}")


if __name__ == "__main__":
    main()
