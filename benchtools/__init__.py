"""Bench tooling that rides alongside the package (sweeps, AOT cost
analysis, profiler capture, regression gate). Repo-root utilities — not
shipped in the wheel; run from a checkout (`python -m benchtools.hlo_cost`,
`python -m benchtools.regression_gate`)."""
