"""Device-free AOT HLO cost analysis of the headline bench configs.

The north-star MFU investigation kept stalling on a dead accelerator
tunnel because every perf tool needed live silicon. This tool does not:
it AOT-lowers the **exact** jitted train-step each headline bench
config dispatches (`net.lower_train_step` — the same `lax.scan`-fused
multi-step `fit(steps_per_execution=k)` and `bench.py` run), then

- runs XLA's cost analysis on the lowered module
  (`jax.stages.Lowered.cost_analysis()` — no backend compile, works on
  any CPU-only host),
- walks the train-step jaxpr primitive-by-primitive for a per-op
  FLOP/byte table (conv/dot counted exactly at 2 FLOPs/MAC — the same
  accounting `bench._count_math_flops` uses for the published MFU —
  everything else estimated at ~1 FLOP/element; `lax.scan` bodies are
  multiplied by their trip count, which XLA's own analysis does NOT do,
  so LSTM-style inner time loops are counted correctly here),
- derives a roofline model (`monitor.xprof.roofline`) against the
  **measured** matmul ceiling from `LASTGOOD_BENCH.json` (the chip's
  demonstrated 111.4 TFLOP/s, not the datasheet) and the device's HBM
  bandwidth: arithmetic intensity, predicted step time, predicted MFU —
  committed, falsifiable numbers the next live tunnel window can
  confirm or refute.

Artifacts: ``<out>/cost_<model>.json`` (default ``PROFILE_aot/``), a
``aot_cost_*{model=}`` gauge set on the monitor registry (served by
``/metrics``), and an in-process cost-report store rendered by the
UIServer's ``/profile`` route.

Usage::

    python -m benchtools.hlo_cost --model resnet50          # one config
    python -m benchtools.hlo_cost --all                     # all four
    python -m benchtools.hlo_cost --model lenet --batch 8 --steps 2

Caveats recorded in every artifact: bytes-accessed figures come from
unoptimized HLO (fusion elides intermediate traffic), so the memory
ceiling is an upper bound on step time and the roofline MFU a lower
bound; `mfu_if_compute_bound` is the matching upper bound. Flash
attention only rides the TPU backend, so transformer lowerings on a
CPU host show the XLA attention fallback (same matmul FLOPs, different
memory traffic).
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# HBM bandwidth GB/s by device-kind substring (public TPU specs) — the
# memory ceiling of the roofline. Same lookup shape as bench._PEAK_TFLOPS.
_PEAK_HBM_GBPS = [
    ("v6", 1640.0), ("trillium", 1640.0), ("v5p", 2765.0), ("v5e", 819.0),
    ("v5 lite", 819.0), ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]
_DEFAULT_HBM_GBPS = 819.0      # unknown TPU-class part: assume v5e

# per-chip ICI (inter-chip interconnect) bandwidth GB/s by device-kind
# substring (public TPU specs; aggregate over links) — the comm ceiling
# the exposed-vs-overlapped accounting measures bucket payloads
# against. Override with --ici-gbps / DL4J_ICI_GBPS when a measured
# all-reduce bandwidth is available.
_PEAK_ICI_GBPS = [
    ("v6", 448.0), ("trillium", 448.0), ("v5p", 600.0), ("v5e", 200.0),
    ("v5 lite", 200.0), ("v4", 300.0), ("v3", 200.0), ("v2", 124.0),
]
_DEFAULT_ICI_GBPS = 200.0      # unknown TPU-class part: assume v5e
# the r04-measured matmul ceiling — used only when no LASTGOOD artifact
# is readable (provenance recorded in the report either way)
_FALLBACK_MEASURED_TFLOPS = 111.4

# ------------------------------------------------------ per-eqn cost model
_ZERO_FLOP = frozenset((
    "reshape", "broadcast_in_dim", "transpose", "slice", "squeeze",
    "concatenate", "pad", "rev", "iota", "convert_element_type",
    "bitcast_convert_type", "copy", "stop_gradient", "device_put",
    "gather", "dynamic_slice", "dynamic_update_slice", "split",
    "expand_dims", "real", "imag",
))


def _nelems(shape) -> float:
    n = 1.0
    for s in shape:
        n *= int(s)
    return n


def _aval_nbytes(aval) -> float:
    try:
        return _nelems(aval.shape) * aval.dtype.itemsize
    except (AttributeError, TypeError):
        return 0.0


def _conv_flops(eqn) -> float:
    """2 FLOPs/MAC conv count — same formula as bench._count_math_flops
    (rhs I-dim is already cin/groups, so no group adjustment)."""
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    kspatial = 1
    for d in dn.rhs_spec[2:]:
        kspatial *= rhs[d]
    cin = rhs[dn.rhs_spec[1]]
    return 2.0 * _nelems(out) * kspatial * cin


def _dot_flops(eqn) -> float:
    a = eqn.invars[0].aval.shape
    b = eqn.invars[1].aval.shape
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    m = 1
    for i, s in enumerate(a):
        if i not in lc and i not in lb:
            m *= s
    n = 1
    for i, s in enumerate(b):
        if i not in rc and i not in rb:
            n *= s
    k = 1
    for i in lc:
        k *= a[i]
    bsz = 1
    for i in lb:
        bsz *= a[i]
    return 2.0 * bsz * m * n * k


def eqn_flops(eqn) -> float:
    """FLOP estimate for one jaxpr equation. conv/dot are exact
    (2 FLOPs/MAC — the accounting the published MFU uses); reductions
    count ~1 FLOP per input element; data movement counts zero;
    everything else (elementwise, transcendentals, RNG) counts ~1 FLOP
    per output element. The estimates are <2% of a conv/matmul net's
    budget — the exact terms dominate."""
    name = eqn.primitive.name
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name == "dot_general":
        return _dot_flops(eqn)
    if name in _ZERO_FLOP:
        return 0.0
    if (name.startswith("reduce_") or name in ("reduce", "argmax", "argmin")
            or name in ("reduce_window", "select_and_scatter_add")):
        return sum(_nelems(v.aval.shape) for v in eqn.invars
                   if hasattr(v.aval, "shape"))
    if name.startswith("scatter"):
        return _nelems(eqn.invars[-1].aval.shape)
    return sum(_nelems(v.aval.shape) for v in eqn.outvars
               if hasattr(v.aval, "shape"))


def eqn_bytes(eqn) -> float:
    """Operand + result bytes of one equation — unfused-HLO traffic,
    an upper bound on what a fusing compiler actually moves."""
    return (sum(_aval_nbytes(v.aval) for v in eqn.invars
                if hasattr(v, "aval"))
            + sum(_aval_nbytes(v.aval) for v in eqn.outvars
                  if hasattr(v, "aval")))


def _sub_jaxprs(eqn):
    subs = []
    for p in eqn.params.values():
        for s in (p if isinstance(p, (list, tuple)) else (p,)):
            inner = getattr(s, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                subs.append(inner)
            elif hasattr(s, "eqns"):
                subs.append(s)
    return subs


def _shape_sig(eqn) -> str:
    def one(v):
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            return "?"
        dt = getattr(aval.dtype, "name", str(aval.dtype))
        return f"{dt}{list(aval.shape)}"
    ins = ",".join(one(v) for v in eqn.invars[:3])
    if len(eqn.invars) > 3:
        ins += ",..."
    outs = ",".join(one(v) for v in eqn.outvars[:2])
    return f"{ins} -> {outs}"


def _walk(jaxpr, mult: int, by_prim: Dict[str, dict], sites: List[dict],
          flags: Dict[str, bool], comm: Optional[Dict[str, dict]] = None):
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            name = eqn.primitive.name
            m = mult
            if name == "scan":
                m = mult * int(eqn.params.get("length", 1) or 1)
            elif name == "while":
                # trip count is data-dependent: body charged once
                flags["while_counted_once"] = True
            elif name == "cond":
                # every branch charged once (only one executes)
                flags["cond_branches_summed"] = True
            for s in subs:
                _walk(s, m, by_prim, sites, flags, comm)
            continue
        f = eqn_flops(eqn) * mult
        b = eqn_bytes(eqn) * mult
        name = eqn.primitive.name
        rec = by_prim.setdefault(
            name, {"op": name, "count": 0, "flops": 0.0, "bytes": 0.0})
        rec["count"] += mult
        rec["flops"] += f
        rec["bytes"] += b
        sites.append({"op": name, "flops": f, "bytes": b,
                      "shape": _shape_sig(eqn)})
        if comm is not None:
            kind = _COLLECTIVE_KINDS.get(name)
            if kind is not None:
                payload = sum(_aval_nbytes(v.aval) for v in eqn.invars
                              if hasattr(v, "aval")) * mult
                crec = comm.setdefault(kind, {"count": 0, "bytes": 0.0})
                crec["count"] += mult
                crec["bytes"] += payload


# jaxpr-level collective primitives → report kind. GSPMD-inserted
# collectives (dense jit paths) never appear in a jaxpr — only programs
# with EXPLICIT collectives (shard_map: the trainers' threshold
# exchange, the gradient_sharing analysis programs) have entries here.
_COLLECTIVE_KINDS = {
    "psum": "all_reduce", "pmin": "all_reduce", "pmax": "all_reduce",
    "all_gather": "all_gather", "all_gather_invariant": "all_gather",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "ppermute": "permute", "pshuffle": "permute",
    "all_to_all": "all_to_all",
}


def _walk_collectives(jaxpr, mult: int, acc: Dict[str, dict]):
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            m = mult
            if eqn.primitive.name == "scan":
                m = mult * int(eqn.params.get("length", 1) or 1)
            for s in subs:
                _walk_collectives(s, m, acc)
            continue
        kind = _COLLECTIVE_KINDS.get(eqn.primitive.name)
        if kind is None:
            continue
        b = sum(_aval_nbytes(v.aval) for v in eqn.invars
                if hasattr(v, "aval")) * mult
        rec = acc.setdefault(kind, {"count": 0, "bytes": 0.0})
        rec["count"] += mult
        rec["bytes"] += b


def _format_collectives(acc: Dict[str, dict], fused_steps: int) -> dict:
    k = max(1, int(fused_steps))
    by = {kind: {"count": rec["count"] / k,
                 "bytes_per_step": rec["bytes"] / k}
          for kind, rec in sorted(acc.items())}
    return {
        "comm_bytes_per_step": sum(r["bytes_per_step"] for r in by.values()),
        "by_collective": by,
        "note": ("operand bytes of explicit collectives per optimizer "
                 "step; GSPMD-inserted collectives (dense jit paths) "
                 "are not visible at the jaxpr level"),
    }


def collective_table(closed_jaxpr, *, fused_steps: int = 1) -> dict:
    """Per-collective byte accounting of a jaxpr: operand (payload)
    bytes of every all-reduce / all-gather / reduce-scatter / permute /
    all-to-all, scan bodies multiplied by trip count, figures divided
    by `fused_steps` — the communication counterpart of `per_op_table`
    (comm volume measured and gated like FLOPs already are)."""
    acc: Dict[str, dict] = {}
    _walk_collectives(getattr(closed_jaxpr, "jaxpr", closed_jaxpr), 1, acc)
    return _format_collectives(acc, fused_steps)


def comm_bytes_block(net, *, n_workers: int = 8, axis: str = "data") -> dict:
    """Dense-vs-threshold gradient-exchange payload for THIS model's
    parameter tree: both exchange programs
    (`gradient_sharing.exchange_jaxpr`) are traced over an AbstractMesh
    — no devices, no mesh, tunnel-independent — and their collectives
    counted by `collective_table`. The committed evidence that the
    threshold wire format moves >= 4x fewer bytes per step. The dense
    program is traced with the model's REAL gradient dtype (the dtype
    policy's compute dtype — bf16 grads under mixed_bf16 halve the
    dense wire)."""
    from deeplearning4j_tpu.parallel import gradient_sharing as gs
    grad_dtype = net.dtype.compute_dtype
    out = {"n_workers": n_workers, "axis": axis,
           "grad_dtype": jnp_dtype_name(grad_dtype),
           "note": ("per-replica all-reduce payload of ONE gradient "
                    "exchange, traced over an AbstractMesh "
                    "(device-free); threshold = int8 sign tensor + "
                    "controller scalars, dense = grad-dtype gradients "
                    "(the dtype policy's compute dtype)")}
    try:
        for mode in ("dense", "threshold"):
            jx = gs.exchange_jaxpr(net.params, mode, n_workers, axis=axis,
                                   grad_dtype=grad_dtype)
            tbl = collective_table(jx)
            out[mode] = tbl
            out[f"{mode}_bytes_per_step"] = tbl["comm_bytes_per_step"]
        if out.get("threshold_bytes_per_step"):
            out["reduction"] = round(out["dense_bytes_per_step"]
                                     / out["threshold_bytes_per_step"], 2)
            # the PR-4 "4x wire format" claim is int8-vs-FP32; under a
            # mixed policy the real dense wire is already bf16 (2x),
            # so both ratios are recorded
            fp32_dense = gs.exchange_wire_bytes(net.params, "dense")
            out["dense_fp32_bytes_per_step"] = fp32_dense
            out["reduction_vs_fp32"] = round(
                fp32_dense / out["threshold_bytes_per_step"], 2)
    except Exception as e:  # noqa: BLE001 — per-version shard_map surface
        out["error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def jnp_dtype_name(dt) -> str:
    import jax.numpy as jnp
    return jnp.dtype(dt).name


def resolve_ici_gbps(ici_gbps: Optional[float] = None,
                     device_kind: str = "") -> dict:
    """ICI-bandwidth ceiling for the overlap accounting: explicit flag
    > DL4J_ICI_GBPS env (a measured all-reduce bandwidth) > public spec
    by device kind. Provenance recorded in the report."""
    if ici_gbps is not None:
        return {"ici_gbps": float(ici_gbps), "ici_source": "--ici-gbps flag"}
    env = os.environ.get("DL4J_ICI_GBPS")
    if env:
        return {"ici_gbps": float(env), "ici_source": "DL4J_ICI_GBPS env"}
    kind = device_kind.lower()
    for key, val in _PEAK_ICI_GBPS:
        if key in kind:
            return {"ici_gbps": val,
                    "ici_source": f"public spec for {key!r}"}
    return {"ici_gbps": _DEFAULT_ICI_GBPS,
            "ici_source": "default (v5e-class public spec)"}


def _overlap_timeline(buckets, peak_flops_s: float, ici_bytes_s: float):
    """Serial-ICI timeline of the bucketed exchange: walking buckets in
    backward ISSUE order (last layer first), bucket i's collective can
    start once its VJP finishes (cumulative backward compute time) and
    once the ICI is free; whatever transfer time extends past the end
    of backward compute is EXPOSED. Returns (exposed_seconds,
    backward_seconds, per-bucket issue table)."""
    t = 0.0
    ici_free = 0.0
    table = []
    for key, bwd_flops, payload in buckets:
        t += bwd_flops / peak_flops_s
        start = max(ici_free, t)
        ici_free = start + payload / ici_bytes_s
        table.append({"bucket": key, "payload_bytes": payload,
                      "backward_flops": bwd_flops,
                      "issue_at_seconds": round(t, 9),
                      "done_at_seconds": round(ici_free, 9)})
    return max(0.0, ici_free - t), t, table


def comm_overlap_block(net, *, backward_flops_per_step: float,
                       peak_tflops: float, n_workers: int = 8,
                       axis: str = "data",
                       ici_gbps: Optional[float] = None,
                       device_kind: str = "",
                       modes=("dense", "threshold", "dense_rs"),
                       bucket_table: bool = True) -> dict:
    """Exposed vs overlapped comm bytes of the bucketed gradient
    exchange (parallel/gradient_sharing.py) for THIS model — the
    roofline-style evidence that per-run bucketing hides collective
    time behind backward compute, measurable tunnel-free.

    Model: buckets (``stacked::`` packed runs + singleton layers, from
    `gradient_sharing.bucket_plan`) issue their collectives in backward
    order; each bucket's payload is its share of the mode's wire bytes
    (`exchange_wire_bytes` on the bucket's sub-tree) and each bucket's
    backward compute budget is the step's backward FLOPs attributed
    proportionally to parameter count (exact for homogeneous stacks,
    an estimate across heterogeneous layers — recorded in the note).
    The single-barrier (PR-4) baseline exposes EVERY byte:
    ``all_at_end_exposed_bytes == total_bytes``, so
    ``exposed_bytes < all_at_end_exposed_bytes`` is the committed
    overlap win."""
    import jax

    import numpy as np

    from deeplearning4j_tpu.parallel import gradient_sharing as gs

    ici = resolve_ici_gbps(ici_gbps, device_kind)
    bw = ici["ici_gbps"] * 1e9
    peak_fs = peak_tflops * 1e12
    plan = gs.bucket_plan(net)
    params = net.params
    grad_dtype = net.dtype.compute_dtype
    total_elems = sum(float(np.prod(np.shape(l)))
                      for l in jax.tree_util.tree_leaves(params))
    rs_plan = gs.rs_shard_plan(params, n_workers)

    out = {
        "n_workers": n_workers,
        "axis": axis,
        "buckets": len(plan),
        "backward_flops_per_step": backward_flops_per_step,
        "peak_tflops": peak_tflops,
        **ici,
        "note": ("bucket = stacked:: packed run or singleton layer; "
                 "collectives issued in backward order against a "
                 "serial-ICI timeline; backward FLOPs attributed to "
                 "buckets by parameter count; payloads = "
                 "exchange_wire_bytes per bucket sub-tree; "
                 "all_at_end_exposed_bytes is the PR-4 single-barrier "
                 "baseline (everything exposed)"),
        "modes": {},
    }
    for mode in modes:
        buckets = []
        for key, members in reversed(plan):
            sub = {m: params[m] for m in members}
            sub_elems = sum(float(np.prod(np.shape(l)))
                            for l in jax.tree_util.tree_leaves(sub))
            payload = gs.exchange_wire_bytes(
                sub, mode, n_workers=n_workers,
                rs_plan={m: rs_plan[m] for m in members}
                if mode in gs.RS_MODES else None,
                grad_dtype=grad_dtype)
            bwd = backward_flops_per_step * (sub_elems
                                             / max(total_elems, 1.0))
            buckets.append((key, bwd, payload))
        exposed_s, bwd_s, table = _overlap_timeline(buckets, peak_fs, bw)
        total_bytes = sum(b[2] for b in buckets)
        exposed_bytes = min(total_bytes, exposed_s * bw)
        entry = {
            "total_bytes": total_bytes,
            "exposed_bytes": exposed_bytes,
            "overlapped_bytes": total_bytes - exposed_bytes,
            "exposed_fraction": (exposed_bytes / total_bytes
                                 if total_bytes else 0.0),
            "exposed_seconds": exposed_s,
            "backward_seconds": bwd_s,
            "all_at_end_exposed_bytes": total_bytes,
        }
        if bucket_table:
            entry["bucket_table"] = table
        out["modes"][mode] = entry
    # headline figures = the sync trainers' DEFAULT program (bucketed
    # dense) — what the aot_comm_overlap_* gauges serve
    head = out["modes"].get("dense") or next(iter(out["modes"].values()))
    for k in ("total_bytes", "exposed_bytes", "overlapped_bytes",
              "exposed_fraction"):
        out[k] = head[k]
    return out


def count_jaxpr_eqns(jaxpr) -> int:
    """Total equation count of a (closed) jaxpr including nested
    sub-jaxprs, each counted ONCE (no trip-count multiplication) — the
    program-SIZE measure scan-over-layers compilation is judged by,
    complementing the trip-multiplied FLOP tables above."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for sub in _sub_jaxprs(eqn):
            n += count_jaxpr_eqns(sub)
    return n


_COMPILE_COLLECTOR = None


def _compile_collector():
    """One process-wide `JitCompileCollector` for every
    `compile_program` call: jax.monitoring's listener list is
    append-only, so a per-call collector would leak one dead listener
    per compile probe (~10 per `--all` run). Readings are taken as
    deltas around each compile."""
    global _COMPILE_COLLECTOR
    if _COMPILE_COLLECTOR is None:
        from deeplearning4j_tpu.monitor import (JitCompileCollector,
                                                MetricsRegistry)
        _COMPILE_COLLECTOR = JitCompileCollector(MetricsRegistry())
    return _COMPILE_COLLECTOR


def compile_program(lowered) -> dict:
    """XLA-compile a lowered train step and record what the compile
    cost: wall seconds, backend-compile seconds + compile count via the
    telemetry core's `JitCompileCollector` (PR-1), and the executable's
    memory analysis (peak temp = activation working set). CPU-safe —
    this is the seam the compile-time regression test and the
    `scripts/verify.sh` smoke build on."""
    coll = _compile_collector().install()
    s0, c0 = coll.compile_seconds(), coll.compile_count()
    out = {}
    t0 = time.perf_counter()
    try:
        compiled = lowered.compile()
        out["compile_seconds"] = round(time.perf_counter() - t0, 3)
        out["xla_backend_compile_seconds"] = round(
            coll.compile_seconds() - s0, 3)
        out["xla_compiles"] = int(coll.compile_count() - c0)
        try:
            mem = compiled.memory_analysis()
            for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                         "temp_size_in_bytes",
                         "generated_code_size_in_bytes"):
                try:
                    out[attr] = int(getattr(mem, attr))
                except (AttributeError, TypeError):
                    pass
            if "temp_size_in_bytes" in out:
                # peak temp == XLA's activation/workspace high-water mark
                out["peak_temp_bytes"] = out["temp_size_in_bytes"]
        except Exception as e:  # noqa: BLE001 — per-backend API surface
            out["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:200]
    except Exception as e:  # noqa: BLE001 — a failed compile still reports
        out["error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        coll.uninstall()
    return out


# deep-stack config for the committed scan-vs-unrolled / remat evidence:
# >= 12 transformer blocks (the acceptance bar), sized so the UNROLLED
# variant still compiles in well under a minute on a CPU host
_DEEP_LM = dict(n_layers=16, d_model=64, n_heads=4, seq_len=128,
                vocab=128, batch=8, steps=2)


def _deep_lm_net(scan_layers: bool, remat_policy=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.transformer import TransformerLM
    c = _DEEP_LM
    lm = TransformerLM(vocab_size=c["vocab"], d_model=c["d_model"],
                       n_layers=c["n_layers"], n_heads=c["n_heads"],
                       max_len=c["seq_len"], remat_policy=remat_policy)
    conf = lm.conf()
    conf.scan_layers = scan_layers
    net = MultiLayerNetwork(conf).init(123)
    x = jax.ShapeDtypeStruct((c["batch"], c["seq_len"]), jnp.float32)
    y = jax.ShapeDtypeStruct((c["batch"], c["seq_len"], c["vocab"]),
                             jnp.float32)
    return net, x, y, c["steps"]


def _deep_lm_probe(scan_layers: bool, remat_policy=None) -> dict:
    net, x, y, steps = _deep_lm_net(scan_layers, remat_policy)
    jaxpr = net.train_step_jaxpr(x, y, steps=steps)
    rep = {"jaxpr_eqn_count": count_jaxpr_eqns(jaxpr)}
    rep.update(compile_program(net.lower_train_step(x, y, steps=steps)))
    return rep


# memoized per _DEEP_LM config: the evidence blocks are
# model-independent, so `--all --deep-compare` must not re-run the
# 5-compile battery once per report
_DEEP_MEMO: Dict[tuple, dict] = {}


def _deep_memo_key(name: str) -> tuple:
    return (name,) + tuple(sorted(_DEEP_LM.items()))


def scan_vs_unrolled() -> dict:
    """CPU-measured evidence for scan-over-layers on a deep stack: the
    SAME >=12-block TransformerLM train step lowered both ways. The
    scan path must compile fewer equations into a smaller program in
    less time — committed so a dead tunnel can't lose the record."""
    key = _deep_memo_key("scan_vs_unrolled")
    if key in _DEEP_MEMO:
        return _DEEP_MEMO[key]
    scan = _deep_lm_probe(scan_layers=True)
    unrolled = _deep_lm_probe(scan_layers=False)
    out = {"config": dict(_DEEP_LM), "scan": scan, "unrolled": unrolled}
    if scan.get("jaxpr_eqn_count") and unrolled.get("jaxpr_eqn_count"):
        out["eqn_reduction"] = round(
            unrolled["jaxpr_eqn_count"] / scan["jaxpr_eqn_count"], 2)
    if scan.get("compile_seconds") and unrolled.get("compile_seconds"):
        out["compile_speedup"] = round(
            unrolled["compile_seconds"] / scan["compile_seconds"], 2)
    _DEEP_MEMO[key] = out
    return out


def remat_compare() -> dict:
    """Peak-temp (activation working set) deltas of the generalized
    remat policies on the same deep stack, scan path: `full` trades ~1
    extra forward of FLOPs for an O(depth)->O(1) activation footprint;
    `dots_saveable` keeps matmul outputs and recomputes the rest."""
    key = _deep_memo_key("remat_compare")
    if key in _DEEP_MEMO:
        return _DEEP_MEMO[key]
    base = _deep_lm_probe(scan_layers=True, remat_policy=None)
    out = {"config": dict(_DEEP_LM),
           "none": {k: base.get(k) for k in ("peak_temp_bytes",
                                             "compile_seconds")}}
    for policy in ("full", "dots_saveable"):
        rep = _deep_lm_probe(scan_layers=True, remat_policy=policy)
        entry = {k: rep.get(k) for k in ("peak_temp_bytes",
                                         "compile_seconds")}
        if rep.get("peak_temp_bytes") and base.get("peak_temp_bytes"):
            entry["temp_reduction"] = round(
                base["peak_temp_bytes"] / rep["peak_temp_bytes"], 2)
        out[policy] = entry
    _DEEP_MEMO[key] = out
    return out


def precision_block(model: str, spec: dict, table: dict, *,
                    batch=None, steps=None) -> dict:
    """fp32-vs-bf16 evidence for one headline config: the SAME model
    traced under both dtype policies, per-op bytes/FLOPs per step from
    the jaxpr walk (no XLA compile — the active policy's program
    section already carries compile evidence), plus the dense-exchange
    wire bytes in each policy's real gradient dtype. The committed
    proof that mixed_bf16 strictly shrinks activation and wire traffic
    (and shifts roofline intensity up) on this program."""
    from deeplearning4j_tpu.parallel import gradient_sharing as gs

    active = spec["net"].dtype.name
    other = "float32" if active != "float32" else "mixed_bf16"

    def policy_entry(pol_name, tbl, net):
        b = tbl["total_bytes_per_step"]
        f = tbl["total_flops_per_step"]
        return {
            "policy": pol_name,
            "bytes_per_step": b,
            "flops_per_step": f,
            "arithmetic_intensity_flop_per_byte": f / max(b, 1.0),
            "wire_bytes_dense": gs.exchange_wire_bytes(
                net.params, "dense", grad_dtype=net.dtype.compute_dtype),
        }

    entries = {active: policy_entry(active, table, spec["net"])}
    spec2 = MODELS[model](batch=batch, steps=steps, policy=other)
    jaxpr2 = spec2["net"].train_step_jaxpr(spec2["x"], spec2["y"],
                                           steps=spec2["steps"])
    table2 = per_op_table(jaxpr2, fused_steps=spec2["steps"], top=1)
    entries[other] = policy_entry(other, table2, spec2["net"])

    fp32 = entries.get("float32")
    bf16 = entries.get("mixed_bf16") or entries.get("custom")
    out = {"active_policy": active, **{k: v for k, v in entries.items()}}
    if fp32 and bf16:
        out["bytes_reduction"] = round(
            fp32["bytes_per_step"] / max(bf16["bytes_per_step"], 1.0), 3)
        out["wire_reduction"] = round(
            fp32["wire_bytes_dense"] / max(bf16["wire_bytes_dense"], 1.0),
            3)
        out["intensity_shift"] = round(
            bf16["arithmetic_intensity_flop_per_byte"]
            / max(fp32["arithmetic_intensity_flop_per_byte"], 1e-12), 3)
    out["note"] = ("per-op jaxpr bytes (unfused operand+result traffic) "
                   "per optimizer step under each dtype policy; wire = "
                   "dense gradient-exchange payload in the policy's "
                   "real grad dtype; bf16 programs must move strictly "
                   "fewer bytes (verify.sh [4/7] asserts)")
    return out


def per_op_table(closed_jaxpr, *, fused_steps: int = 1,
                 top: int = 10) -> dict:
    """Per-op cost table for a (fused) train-step jaxpr. `lax.scan`
    bodies are multiplied by trip count, and the program totals divided
    by `fused_steps` (the top-level steps-per-execution scan), so every
    figure is **per optimizer step** — including inner time loops XLA's
    own cost analysis charges only once."""
    by_prim: Dict[str, dict] = {}
    sites: List[dict] = []
    flags: Dict[str, bool] = {}
    comm_acc: Dict[str, dict] = {}
    _walk(closed_jaxpr.jaxpr, 1, by_prim, sites, flags, comm_acc)
    total_f = sum(r["flops"] for r in by_prim.values())
    total_b = sum(r["bytes"] for r in by_prim.values())
    conv_dot = sum(by_prim.get(k, {}).get("flops", 0.0)
                   for k in ("conv_general_dilated", "dot_general"))
    k = max(1, int(fused_steps))
    top_sites = heapq.nlargest(top, sites, key=lambda s: s["flops"])
    denom = max(total_f, 1.0)

    def per_step(rec):
        # EVERY figure in the tables is per optimizer step (the whole-
        # program totals only appear under total_flops/total_bytes) —
        # so table rows are directly comparable to the *_per_step keys
        out = dict(rec)
        out["flops"] = rec["flops"] / k
        out["bytes"] = rec["bytes"] / k
        if "count" in rec:
            out["count"] = rec["count"] / k
        out["share"] = round(rec["flops"] / denom, 4)
        return out
    return {
        "fused_steps": k,
        "total_flops": total_f,
        "total_bytes": total_b,
        # accumulated in the SAME walk as the FLOP/byte tables (a
        # second full-jaxpr traversal measurably doubled
        # jaxpr_walk_seconds on ResNet-50)
        "collectives": _format_collectives(comm_acc, k),
        "total_flops_per_step": total_f / k,
        "total_bytes_per_step": total_b / k,
        "conv_dot_flops_per_step": conv_dot / k,
        "by_primitive": sorted((per_step(r) for r in by_prim.values()),
                               key=lambda r: -r["flops"]),
        "top10": [per_step(s) for s in top_sites],
        "flags": flags,
        "note": ("per-step figures: scan bodies x trip count, divided by "
                 "fused_steps (tables AND totals_per_step); conv/dot "
                 "exact at 2 FLOPs/MAC, other ops ~1 FLOP/element; bytes "
                 "are unfused operand+result traffic (upper bound)"),
    }


# ------------------------------------------------------------ model builders
def _resolve_builder_policy(policy, default="mixed_bf16"):
    """Builder-level policy resolution: an EXPLICIT `policy=` is a
    measurement seam (the precision_block's fp32-vs-bf16 counterfactual
    trace) and must win over the DL4J_DTYPE_POLICY env override —
    otherwise the env A/B would silently trace BOTH sides of the
    comparison under the same policy and the evidence degenerates to
    1.0 ratios. `policy=None` (the CLI default) still honors the env,
    so headline reports remain A/B-able."""
    from deeplearning4j_tpu.nd.dtype import as_policy, env_policy
    if policy is not None:
        return as_policy(policy)
    return env_policy() or as_policy(default)


def _policy_net(conf, policy, seed=123):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(conf, dtype_policy=policy)
    # pin the resolved policy past the container's own env-aware
    # resolution (env semantics were already applied above)
    net.dtype = policy
    return net.init(seed)


def build_mlp(batch=None, steps=None, policy=None):
    """Tiny dense net — the golden-test config (not a bench headline)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    batch, steps = batch or 16, steps or 2
    pol = _resolve_builder_policy(policy, default="float32")
    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    net = _policy_net(conf, pol, seed=conf.seed)
    x = jax.ShapeDtypeStruct((batch, 4), jnp.float32)
    y = jax.ShapeDtypeStruct((batch, 3), jnp.float32)
    return dict(model="mlp", net=net, x=x, y=y, steps=steps,
                examples_per_step=batch, unit="examples/sec",
                measured_path=None,
                config={"batch": batch, "steps": steps,
                        "dtype_policy": pol.name})


def build_lenet(batch=None, steps=None, policy=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.lenet import LeNet
    batch, steps = batch or 128, steps or 100
    pol = _resolve_builder_policy(policy)
    net = _policy_net(LeNet(num_classes=10).conf(), pol)
    x = jax.ShapeDtypeStruct((batch, 28, 28, 1), jnp.float32)
    y = jax.ShapeDtypeStruct((batch, 10), jnp.float32)
    return dict(model="lenet", net=net, x=x, y=y, steps=steps,
                examples_per_step=batch, unit="images/sec",
                measured_path=("extras", "lenet_mnist", "value"),
                config={"batch": batch, "steps": steps,
                        "dtype_policy": pol.name})


def build_resnet50(batch=None, steps=None, policy=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.common.updaters import Nesterovs
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.resnet50 import ResNet50
    batch, steps = batch or 128, steps or 20
    pol = _resolve_builder_policy(policy)
    model = ResNet50(num_classes=1000, height=224, width=224, channels=3)
    conf = model.conf()
    # same bench-only lr override bench_resnet50 applies — identical
    # FLOPs, and keeps this lowering byte-for-byte the headline program
    for node in conf.nodes.values():
        if node.layer is not None and getattr(node.layer, "updater",
                                              None) is not None:
            node.layer.updater = Nesterovs(0.005, 0.9)
    net = ComputationGraph(conf, dtype_policy=pol)
    net.dtype = pol          # see _policy_net: explicit policy is final
    net.init(model.seed)
    x = jax.ShapeDtypeStruct((batch, 224, 224, 3),
                             pol.compute_dtype)
    y = jax.ShapeDtypeStruct((batch, 1000), jnp.float32)
    return dict(model="resnet50", net=net, x=x, y=y, steps=steps,
                examples_per_step=batch, unit="images/sec",
                measured_path=("value",),
                config={"batch": batch, "image_size": 224, "steps": steps,
                        "dtype_policy": pol.name})


def build_transformer(batch=None, steps=None, policy=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.transformer import TransformerLM
    B, T, V = batch or 16, 256, 512
    steps = steps or 30
    pol = _resolve_builder_policy(policy)
    lm = TransformerLM(vocab_size=V, d_model=256, n_layers=4, n_heads=8,
                       max_len=T)
    net = _policy_net(lm.conf(), pol)
    x = jax.ShapeDtypeStruct((B, T), jnp.float32)
    y = jax.ShapeDtypeStruct((B, T, V), jnp.float32)
    return dict(model="transformer", net=net, x=x, y=y, steps=steps,
                examples_per_step=B * T, unit="tokens/sec",
                measured_path=("extras", "transformer_lm", "value"),
                config={"batch": B, "seq_len": T, "d_model": 256,
                        "n_layers": 4, "n_heads": 8, "vocab": V,
                        "dtype_policy": pol.name,
                        "attention": ("xla fallback — flash attention "
                                      "rides only the TPU backend; same "
                                      "matmul FLOPs")})


def build_lstm(batch=None, steps=None, policy=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM
    B, T, V = batch or 64, 100, 77
    steps = steps or 50
    pol = _resolve_builder_policy(policy)
    net = _policy_net(TextGenerationLSTM(vocab_size=V).conf(), pol)
    x = jax.ShapeDtypeStruct((B, T, V), jnp.float32)
    y = jax.ShapeDtypeStruct((B, T, V), jnp.float32)
    return dict(model="lstm", net=net, x=x, y=y, steps=steps,
                examples_per_step=B * T, unit="chars/sec",
                measured_path=("extras", "lstm_char_rnn", "value"),
                config={"batch": B, "seq_len": T, "vocab": V,
                        "dtype_policy": pol.name})


MODELS = {
    "mlp": build_mlp,
    "lenet": build_lenet,
    "resnet50": build_resnet50,
    "transformer": build_transformer,
    "lstm": build_lstm,
}
HEADLINE_MODELS = ("lenet", "resnet50", "transformer", "lstm")


# ----------------------------------------------------------- peak resolution
def _dig(d, path):
    for p in path:
        if not isinstance(d, dict):
            return None
        d = d.get(p)
    return d


def resolve_peaks(peak_tflops: Optional[float] = None,
                  hbm_gbps: Optional[float] = None) -> dict:
    """Compute/memory ceilings for the roofline. Priority: explicit
    flags > LASTGOOD_BENCH.json's measured matmul probe (the chip's
    demonstrated ceiling) > the committed r04 measurement."""
    from deeplearning4j_tpu import bench
    lastgood = bench._load_lastgood()
    kind = str((lastgood or {}).get("device_kind", "v5 lite")).lower()
    if hbm_gbps is None:
        hbm_gbps = _DEFAULT_HBM_GBPS
        for key, val in _PEAK_HBM_GBPS:
            if key in kind:
                hbm_gbps = val
                break
    if peak_tflops is not None:
        source = "explicit --peak-tflops flag"
    elif lastgood and lastgood.get("measured_matmul_tflops"):
        peak_tflops = float(lastgood["measured_matmul_tflops"])
        source = ("LASTGOOD_BENCH.json measured_matmul_tflops "
                  f"({lastgood.get('measured_at', '?')})")
    else:
        peak_tflops = _FALLBACK_MEASURED_TFLOPS
        source = "BENCH_r04 measured ceiling (no LASTGOOD artifact readable)"
    return {"peak_tflops": float(peak_tflops), "hbm_gbps": float(hbm_gbps),
            "device_kind": kind, "peak_source": source,
            "lastgood": lastgood}


# ------------------------------------------------------------------ analyze
def analyze(model: str, *, batch: Optional[int] = None,
            steps: Optional[int] = None, top: int = 10,
            peak_tflops: Optional[float] = None,
            hbm_gbps: Optional[float] = None,
            ici_gbps: Optional[float] = None,
            compile_exe: bool = False, program: bool = True,
            deep_compare: Optional[bool] = None) -> dict:
    """Full AOT cost analysis of one headline config: lower the exact
    train-step, run XLA cost analysis, build the per-op table and the
    roofline, and compare predictions against the last good chip
    measurement. `program=True` additionally XLA-compiles the lowering
    and records the program section (jaxpr equation count, compile
    seconds via `JitCompileCollector`, peak-temp/activation bytes).
    `deep_compare` (default: transformer only) embeds the committed
    scan-vs-unrolled + remat-policy evidence blocks. Returns the report
    dict (what ``cost_<model>.json`` contains)."""
    from deeplearning4j_tpu.monitor.xprof import roofline
    if model not in MODELS:
        raise ValueError(f"unknown model {model!r}: {sorted(MODELS)}")
    spec = MODELS[model](batch=batch, steps=steps)
    net, x, y, k = spec["net"], spec["x"], spec["y"], spec["steps"]

    t0 = time.perf_counter()
    lowered = net.lower_train_step(x, y, steps=k)
    lower_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    try:
        xla = dict(lowered.cost_analysis() or {})
    except Exception as e:  # noqa: BLE001 — per-backend API surface
        xla = {"error": f"{type(e).__name__}: {e}"[:200]}
    xla_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    jaxpr = net.train_step_jaxpr(x, y, steps=k)
    table = per_op_table(jaxpr, fused_steps=k, top=top)
    table_s = time.perf_counter() - t0

    peaks = resolve_peaks(peak_tflops, hbm_gbps)
    lastgood = peaks.pop("lastgood")
    peak_fs = peaks["peak_tflops"] * 1e12
    peak_bs = peaks["hbm_gbps"] * 1e9
    roof = roofline(table["total_flops_per_step"],
                    table["total_bytes_per_step"], peak_fs, peak_bs)

    model_flops = table["conv_dot_flops_per_step"]
    t_pred = roof["predicted_step_seconds"]
    predicted = {
        "step_seconds": t_pred,
        "throughput": spec["examples_per_step"] / t_pred,
        "unit": spec["unit"],
        "examples_per_step": spec["examples_per_step"],
        # standard MFU definition: model (conv+dot) FLOPs over wall time
        # x peak — lower bound (memory ceiling uses unfused bytes)...
        "mfu": model_flops / (t_pred * peak_fs),
        # ...and the matching upper bound at the compute ceiling
        "mfu_if_compute_bound": (
            model_flops / max(table["total_flops_per_step"], 1.0)),
        "mfu_note": ("mfu = conv+dot FLOPs (2/MAC — the published MFU "
                     "accounting) / (predicted step time x measured "
                     "matmul ceiling); true value should land in "
                     "[mfu, mfu_if_compute_bound]"),
    }

    report = {
        "model": model,
        "config": spec["config"],
        "generated_by": "benchtools/hlo_cost.py (AOT, device-free)",
        "lowering": {
            "backend": _backend_name(),
            "fused_steps": k,
            "lower_seconds": round(lower_s, 3),
            "xla_cost_analysis_seconds": round(xla_s, 3),
            "jaxpr_walk_seconds": round(table_s, 3),
        },
        "xla_cost_analysis": _trim_xla(xla),
        "per_op": table,
        "roofline": {**roof, **peaks},
        "predicted": predicted,
    }
    if program:
        from deeplearning4j_tpu.nn import scan_stack
        prog = {"jaxpr_eqn_count": count_jaxpr_eqns(jaxpr),
                "scan_layers": scan_stack.scan_enabled(net.conf),
                # dense-vs-threshold gradient-exchange payload for this
                # model's param tree (gradient_sharing wire format) —
                # the committed comm-bytes evidence, device-free
                "comm_bytes": comm_bytes_block(net)}
        try:
            # exposed-vs-overlapped comm bytes of the (default)
            # bucketed exchange: per-bucket payloads against the
            # backward FLOPs available to hide them — backward ~2x
            # forward ~2/3 of the step's total
            prog["comm_overlap"] = comm_overlap_block(
                net,
                backward_flops_per_step=(
                    table["total_flops_per_step"] * 2.0 / 3.0),
                peak_tflops=peaks["peak_tflops"],
                ici_gbps=ici_gbps,
                device_kind=peaks["device_kind"])
        except Exception as e:  # noqa: BLE001 — per-model plan surface
            prog["comm_overlap"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        prog.update(compile_program(lowered))
        report["program"] = prog
        try:
            # fp32-vs-bf16 dtype-policy evidence (jaxpr walk only — no
            # second XLA compile; ~2x jaxpr_walk_seconds)
            report["precision"] = precision_block(model, spec, table,
                                                  batch=batch, steps=steps)
        except Exception as e:  # noqa: BLE001 — per-model surface
            report["precision"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if deep_compare is None:
        # the evidence battery XLA-compiles five deep-stack programs —
        # honoring --no-program's "no XLA compile" promise means it
        # must not run unless explicitly requested
        deep_compare = program and model == "transformer"
    if deep_compare:
        report["scan_vs_unrolled"] = scan_vs_unrolled()
        report["remat_compare"] = remat_compare()
    measured = _measured_block(spec, lastgood, predicted)
    if measured:
        report["measured"] = measured
    if compile_exe:
        if program:
            # the program section already compiled this exact lowering
            # — don't pay the (minutes-long for ResNet on CPU) XLA
            # compile a second time for the same numbers
            keep = ("compile_seconds", "argument_size_in_bytes",
                    "output_size_in_bytes", "temp_size_in_bytes",
                    "generated_code_size_in_bytes", "error")
            report["compiled"] = {k: report["program"][k]
                                  for k in keep if k in report["program"]}
        else:
            report["compiled"] = _compiled_block(lowered)
    return report


def _backend_name() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:  # noqa: BLE001
        return "?"


def _trim_xla(xla: dict) -> dict:
    """Headline keys of XLA's analysis (the full dict carries one
    'bytes accessedN{}' entry per parameter — hundreds for ResNet)."""
    keep = {k: v for k, v in xla.items()
            if k in ("flops", "bytes accessed", "transcendentals",
                     "optimal_seconds", "error")}
    keep["note"] = ("unoptimized-HLO analysis; scan/while bodies counted "
                    "ONCE by XLA (inner time loops under-counted — the "
                    "per_op table multiplies trip counts instead)")
    return keep


def _compiled_block(lowered) -> dict:
    t0 = time.perf_counter()
    try:
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        out = {"compile_seconds": round(time.perf_counter() - t0, 3)}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes"):
            try:
                out[attr] = int(getattr(mem, attr))
            except (AttributeError, TypeError):
                pass
        return out
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _measured_block(spec, lastgood, predicted) -> Optional[dict]:
    if not lastgood or not spec.get("measured_path"):
        return None
    thr = _dig(lastgood, spec["measured_path"])
    if not isinstance(thr, (int, float)) or thr <= 0:
        return None
    meas_step_s = spec["examples_per_step"] / float(thr)
    out = {
        "throughput": float(thr),
        "unit": spec["unit"],
        "step_seconds": meas_step_s,
        "source": "LASTGOOD_BENCH.json",
        "measured_at": lastgood.get("measured_at"),
        "stale": bool(lastgood.get("stale", False)),
        "predicted_over_measured_step_time": (
            predicted["step_seconds"] / meas_step_s),
    }
    if spec["model"] == "resnet50" and lastgood.get("mfu") is not None:
        out["mfu"] = lastgood["mfu"]
        out["mfu_vs_effective_peak"] = lastgood.get("mfu_vs_effective_peak")
    return out


# ---------------------------------------------------------------------- CLI
def run(models, *, out_dir: str = "PROFILE_aot", batch=None, steps=None,
        top: int = 10, peak_tflops=None, hbm_gbps=None, ici_gbps=None,
        compile_exe: bool = False, program: bool = True,
        deep_compare: Optional[bool] = None,
        publish: bool = True) -> List[dict]:
    from deeplearning4j_tpu.monitor import xprof
    os.makedirs(out_dir, exist_ok=True)
    reports = []
    for m in models:
        rep = analyze(m, batch=batch, steps=steps, top=top,
                      peak_tflops=peak_tflops, hbm_gbps=hbm_gbps,
                      ici_gbps=ici_gbps,
                      compile_exe=compile_exe, program=program,
                      deep_compare=deep_compare)
        path = os.path.join(out_dir, f"cost_{m}.json")
        with open(path, "w") as f:
            json.dump(rep, f, indent=1, default=str)
            f.write("\n")
        if publish:
            xprof.publish_cost_report(rep)
        p, pr = rep["per_op"], rep["predicted"]
        line = {
            "model": m,
            "flops_per_step": round(p["total_flops_per_step"]),
            "conv_dot_flops_per_step": round(p["conv_dot_flops_per_step"]),
            "bytes_per_step": round(p["total_bytes_per_step"]),
            "arithmetic_intensity": round(
                rep["roofline"]["arithmetic_intensity_flop_per_byte"], 2),
            "bound": rep["roofline"]["bound"],
            "predicted_step_ms": round(pr["step_seconds"] * 1e3, 3),
            "predicted_mfu": round(pr["mfu"], 4),
            "mfu_if_compute_bound": round(pr["mfu_if_compute_bound"], 4),
            "top_op": (p["top10"][0]["op"] if p["top10"] else None),
            "artifact": path,
        }
        prog = rep.get("program")
        if prog:
            line["jaxpr_eqn_count"] = prog.get("jaxpr_eqn_count")
            line["compile_seconds"] = prog.get("compile_seconds")
            line["peak_temp_bytes"] = prog.get("peak_temp_bytes")
            cb = prog.get("comm_bytes") or {}
            line["comm_bytes_dense"] = cb.get("dense_bytes_per_step")
            line["comm_bytes_threshold"] = cb.get("threshold_bytes_per_step")
            line["comm_reduction"] = cb.get("reduction")
            co = prog.get("comm_overlap") or {}
            line["comm_exposed_bytes"] = co.get("exposed_bytes")
            line["comm_overlapped_bytes"] = co.get("overlapped_bytes")
        prec = rep.get("precision") or {}
        if prec.get("bytes_reduction"):
            line["precision_bytes_reduction"] = prec["bytes_reduction"]
            line["precision_wire_reduction"] = prec.get("wire_reduction")
        svu = rep.get("scan_vs_unrolled")
        if svu:
            line["scan_eqn_reduction"] = svu.get("eqn_reduction")
            line["scan_compile_speedup"] = svu.get("compile_speedup")
        print(json.dumps(line), flush=True)
        reports.append(rep)
    return reports


def main(argv=None) -> int:
    # tunnel-independent by construction: force the CPU backend before
    # any device touch (the axon plugin's sitecustomize would otherwise
    # try — and with a dead tunnel hang — to init the TPU client)
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend may already be up (tests)
        pass
    ap = argparse.ArgumentParser(
        prog="benchtools.hlo_cost",
        description="Device-free AOT HLO cost analysis of the headline "
                    "bench configs")
    ap.add_argument("--model", choices=sorted(MODELS), action="append",
                    help="config(s) to analyze (repeatable)")
    ap.add_argument("--all", action="store_true",
                    help=f"all headline configs: {', '.join(HEADLINE_MODELS)}")
    ap.add_argument("--out", default="PROFILE_aot",
                    help="artifact directory (cost_<model>.json)")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the headline batch size")
    ap.add_argument("--steps", type=int, default=None,
                    help="override fused steps-per-execution")
    ap.add_argument("--top", type=int, default=10, help="top-N op table size")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="compute ceiling override (default: measured "
                         "matmul probe from LASTGOOD_BENCH.json)")
    ap.add_argument("--hbm-gbps", type=float, default=None,
                    help="memory-bandwidth ceiling override")
    ap.add_argument("--ici-gbps", type=float, default=None,
                    help="ICI-bandwidth ceiling for the exposed-vs-"
                         "overlapped comm accounting (default: "
                         "DL4J_ICI_GBPS env, else public spec by "
                         "device kind)")
    ap.add_argument("--compile", action="store_true", dest="compile_exe",
                    help="also record the legacy `compiled` block "
                         "(superseded by the default `program` section)")
    ap.add_argument("--no-program", action="store_false", dest="program",
                    help="skip the program section (no XLA compile: "
                         "faster, but drops compile_seconds/peak-memory)")
    ap.add_argument("--deep-compare", action="store_true", default=None,
                    dest="deep_compare",
                    help="embed scan-vs-unrolled + remat-policy evidence "
                         "blocks (default: transformer only)")
    args = ap.parse_args(argv)
    models = list(args.model or [])
    if args.all or not models:
        models = list(HEADLINE_MODELS)
    run(models, out_dir=args.out, batch=args.batch, steps=args.steps,
        top=args.top, peak_tflops=args.peak_tflops, hbm_gbps=args.hbm_gbps,
        ici_gbps=args.ici_gbps,
        compile_exe=args.compile_exe, program=args.program,
        deep_compare=args.deep_compare)
    return 0


if __name__ == "__main__":
    sys.exit(main())
