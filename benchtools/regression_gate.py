"""Bench regression gate CLI — exits nonzero on an unexplained
throughput drop.

Wraps `deeplearning4j_tpu.bench.compare_bench`: a structural,
per-metric-tolerance comparison of a fresh BENCH JSON against the
committed last-known-good artifact. Stale fallbacks (tunnel died —
the "fresh" record is the baseline echo with provenance), CPU-sandbox
runs (different platform), and first runs (no baseline) are explained
outcomes and exit 0 with a distinct status; only a genuine regression
exits 1.

Usage::

    python -m benchtools.regression_gate FRESH.json [BASELINE.json]
        [--tolerance 0.10] [--recompute]

FRESH may be a raw BENCH record, a driver round wrapper
(``{"parsed": {...}}`` — the committed ``BENCH_r0N.json`` shape), or a
log whose LAST line is the record (what ``python bench.py | tee`` leaves
behind). BASELINE defaults to the repo's ``LASTGOOD_BENCH.json``.

If the fresh record already embeds a ``regression_check`` block (bench
main() computes one against the pre-run baseline before refreshing the
artifact), that verdict is used — comparing against the now-refreshed
LASTGOOD would be fresh-vs-fresh and always pass. ``--recompute`` (or an
explicit BASELINE argument) forces a fresh comparison instead.

Exit codes: 0 pass / explained (stale, incomparable, no baseline),
1 regression, 2 usage or unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import bench  # noqa: E402

_EXPLAINED = ("pass", "stale_fallback", "incomparable_platform",
              "no_baseline", "no_measurement")


def load_record(path: str) -> dict:
    """Accept a raw record, a driver round wrapper, or a JSONL log whose
    last parseable line is the record."""
    with open(path) as f:
        text = f.read()
    try:
        rec = json.loads(text)
    except ValueError:
        rec = None
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
                break
            except ValueError:
                continue
        if rec is None:
            raise ValueError(f"no JSON record found in {path}")
    if isinstance(rec, dict) and isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]          # committed BENCH_r0N.json wrapper
    if not isinstance(rec, dict):
        raise ValueError(f"{path} is not a JSON object")
    return rec


def run_gate(fresh: dict, baseline=None, *, tolerance=None,
             recompute: bool = False) -> dict:
    """Resolve the gate verdict for a loaded record (library seam the
    tests drive). Embedded verdicts win unless `recompute`, an explicit
    baseline, OR a tolerance override asks otherwise — the embedded
    block was computed at the default tolerance, so honoring it while
    the caller passes --tolerance would silently ignore the flag."""
    embedded = fresh.get("regression_check")
    if (isinstance(embedded, dict) and not recompute and baseline is None
            and tolerance is None):
        return {**embedded, "verdict_source": "embedded regression_check "
                                              "(vs pre-run baseline)"}
    if baseline is None:
        baseline = bench._load_lastgood()
    kw = {}
    if tolerance is not None:
        kw["default_tolerance"] = tolerance
    report = bench.compare_bench(fresh, baseline, **kw)
    report["verdict_source"] = "recomputed vs baseline artifact"
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchtools.regression_gate")
    ap.add_argument("fresh", help="fresh BENCH JSON (record, driver "
                                  "wrapper, or log w/ last-line JSON)")
    ap.add_argument("baseline", nargs="?", default=None,
                    help="baseline record (default: LASTGOOD_BENCH.json; "
                         "passing one forces recompute)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override the default relative-drop tolerance "
                         f"(default {bench.GATE_DEFAULT_TOLERANCE}); "
                         "implies recomputing against the baseline "
                         "artifact (the embedded verdict used the "
                         "default)")
    ap.add_argument("--recompute", action="store_true",
                    help="ignore an embedded regression_check block")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the JSON report (status line only)")
    args = ap.parse_args(argv)
    try:
        fresh = load_record(args.fresh)
        baseline = load_record(args.baseline) if args.baseline else None
    except (OSError, ValueError) as e:
        print(f"regression-gate: cannot load input: {e}", file=sys.stderr)
        return 2
    report = run_gate(fresh, baseline, tolerance=args.tolerance,
                      recompute=args.recompute)
    status = report.get("status", "regression")
    if not args.quiet:
        print(json.dumps(report, indent=1, default=str))
    nreg = len(report.get("regressions", []) or [])
    print(f"regression-gate: {status}"
          + (f" ({nreg} metric(s) past tolerance)" if nreg else ""))
    return 0 if status in _EXPLAINED else 1


if __name__ == "__main__":
    sys.exit(main())
