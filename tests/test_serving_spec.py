"""Speculative decoding + copy-on-write shared-prefix block reuse.

Two contracts anchor this suite (docs/SERVING.md):

- SPECULATIVE GREEDY IS VANILLA GREEDY, bit-for-bit: the n-gram
  proposer's drafts are scored by one k-position target dispatch and
  the first disagreement truncates to the target's own token — so
  whatever the drafts were, the emitted stream equals whole-batch
  `generate()` exactly (staggered admissions, chunk/spec interleaving,
  preempt-requeue continuations, mixed greedy+sampled waves included).
- SHARED-PREFIX STREAMS ARE PRIVATE-BLOCK STREAMS, bit-for-bit: a
  prefix prefilled once and mapped copy-on-write must emit exactly
  what a full private prefill would — through noise-filled pools,
  mid-block tail forks, evictions while shared, and exact-match
  admissions that never run a forward pass at all.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.serving import (
    BlockAllocator,
    GenerationServer,
    PagedDecodeEngine,
)
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 32
BL = 4


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net():
    return tiny_lm()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 5))


@pytest.fixture(scope="module")
def ref_tokens(net, prompts):
    return generate(net, prompts, 20, temperature=0)    # [6, 20]


def drain(eng, slot2req, out, **step_kw):
    guard = 0
    while eng.active.any():
        emitted, finished = eng.step(**step_kw)
        for slot, toks in emitted.items():
            out[slot2req[slot]].extend(toks)
        for slot in finished:
            del slot2req[slot]
        guard += 1
        assert guard < 400, "engine failed to drain"


def admit_all(eng, reqs):
    """Admit every request (asserts capacity), returning ({slot: req
    index}, {req index: [first token]})."""
    admitted = eng.admit_many(reqs)
    assert len(admitted) == len(reqs)
    s2r, out = {}, {}
    for i, (slot, first, done) in enumerate(admitted):
        out[i] = [first]
        if not done:
            s2r[slot] = i
    return s2r, out


# --------------------------------------------------------------------------
class TestAllocatorRefcounts:
    def test_share_free_cycle(self):
        a = BlockAllocator(8)
        got = a.allocate(3)
        assert all(a.refcount(b) == 1 for b in got)
        a.share(got)
        assert all(a.refcount(b) == 2 for b in got)
        assert a.shared_blocks == 3
        assert a.free_blocks == 4
        a.free(got)                      # one holder lets go
        assert a.free_blocks == 4        # still granted to the other
        assert a.shared_blocks == 0
        a.free(got)                      # last holder
        assert a.free_blocks == 7
        assert all(a.refcount(b) == 0 for b in got)

    def test_share_of_free_block_rejected(self):
        a = BlockAllocator(4)
        got = a.allocate(1)
        a.free(got)
        with pytest.raises(ValueError, match="not granted"):
            a.share(got)

    def test_double_free_under_sharing(self):
        """The double-free guard extends to refcounts: dropping more
        references than held raises, and the failed batch mutates
        NOTHING (no half-freed allocator state)."""
        a = BlockAllocator(8)
        got = a.allocate(2)
        a.share([got[0]])                # got[0] rc=2, got[1] rc=1
        a.free(got)                      # rc 1 / 0
        with pytest.raises(ValueError, match="double-free"):
            a.free(got)                  # got[1] has no refs left
        # the batch failed atomically: got[0]'s surviving ref intact
        assert a.refcount(got[0]) == 1
        a.free([got[0]])
        assert a.free_blocks == 7
        # a list naming one block more times than it holds refs
        b = a.allocate(1)
        with pytest.raises(ValueError, match="double-free"):
            a.free(b + b)
        assert a.refcount(b[0]) == 1

    def test_fragmented_churn_with_refcounts(self):
        """Interleaved allocate/share/free churn: the free list must
        never hand out a block that still carries references, and the
        accounting must come back to a full pool."""
        a = BlockAllocator(16)
        rng = np.random.default_rng(0)
        held = []                        # lists of blocks with one ref
        for _ in range(200):
            op = rng.integers(0, 3)
            if op == 0:
                got = a.allocate(int(rng.integers(1, 4)))
                if got is not None:
                    assert all(a.refcount(b) == 1 for b in got)
                    held.append(got)
            elif op == 1 and held:
                blocks = held[int(rng.integers(len(held)))]
                a.share(blocks)
                held.append(list(blocks))
            elif op == 2 and held:
                blocks = held.pop(int(rng.integers(len(held))))
                a.free(blocks)
            # free list and refs never overlap
            assert all(a.refcount(b) == 0 for b in a._free)
        for blocks in held:
            a.free(blocks)
        assert a.free_blocks == 15


# --------------------------------------------------------------------------
class TestProposer:
    def _eng(self, net):
        return PagedDecodeEngine(net, n_slots=2, n_blocks=24,
                                 block_len=BL, speculative=4)

    def test_ngram_continuation_and_recency(self, net):
        eng = self._eng(net)
        s2r, out = admit_all(eng, [dict(prompt_ids=np.arange(5) % V,
                                        n_tokens=2)])
        slot = next(iter(s2r))
        # history ends ...7, 8] with an earlier [7, 8, 9] and a LATER
        # [7, 8, 5]: the most recent occurrence wins
        eng.slots[slot].history = [1, 7, 8, 9, 2, 7, 8, 5, 6, 7, 8]
        assert eng._propose(slot, 3) == [5, 6, 7]
        # longest n-gram wins over a shorter, more recent one
        eng.slots[slot].history = [3, 7, 8, 4, 1, 3, 7, 8, 9, 3, 7, 8]
        assert eng._propose(slot, 2) == [9, 3]

    def test_no_match_proposes_nothing(self, net):
        eng = self._eng(net)
        s2r, out = admit_all(eng, [dict(prompt_ids=np.arange(5) % V,
                                        n_tokens=2)])
        slot = next(iter(s2r))
        eng.slots[slot].history = [1, 2, 3, 4, 5]
        assert eng._propose(slot, 3) == []
        assert eng._propose(slot, 0) == []

    def test_cyclic_history_is_acceptance_friendly(self, net):
        """A repeating tail — what greedy decode of a converged stream
        looks like — drafts the whole cycle ahead."""
        eng = self._eng(net)
        s2r, out = admit_all(eng, [dict(prompt_ids=np.arange(5) % V,
                                        n_tokens=2)])
        slot = next(iter(s2r))
        eng.slots[slot].history = [9, 4, 5, 4, 5, 4, 5]
        # the continuation runs to the end of history (the proposer
        # copies, it does not extrapolate the cycle)
        assert eng._propose(slot, 3) == [4, 5]
        assert eng._propose(slot, 1) == [4]


# --------------------------------------------------------------------------
class TestSpeculativeParity:
    def test_spec_greedy_bit_equal_generate(self, net, prompts,
                                            ref_tokens):
        eng = PagedDecodeEngine(net, n_slots=6, n_blocks=64,
                                block_len=BL, speculative=4)
        s2r, out = admit_all(eng, [dict(prompt_ids=prompts[i],
                                        n_tokens=20) for i in range(6)])
        drain(eng, s2r, out)
        got = np.asarray([out[i] for i in range(6)])
        np.testing.assert_array_equal(got, ref_tokens)
        assert eng.spec_dispatches_total > 0
        # every post-admission token (19 per stream — admission emits
        # the first) went through the speculative dispatch path
        assert eng.spec_emitted_total == 6 * 19

    def test_staggered_admissions_and_chunk_interleaving(
            self, net, prompts, ref_tokens):
        """Admissions landing mid-speculation plus alternating
        speculative and chunked dispatches — the scheduler's
        accept-rate fallback does exactly this."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=64,
                                block_len=BL, speculative=4,
                                steps_per_dispatch=3)
        s2r, out = admit_all(eng, [dict(prompt_ids=prompts[i],
                                        n_tokens=20) for i in range(2)])
        emitted, _ = eng.step(speculate=True)
        for slot, toks in emitted.items():
            out[s2r[slot]].extend(toks)
        more = eng.admit_many([dict(prompt_ids=prompts[i], n_tokens=20)
                               for i in (2, 3)])
        assert len(more) == 2
        for j, (slot, first, done) in enumerate(more):
            out[2 + j] = [first]
            s2r[slot] = 2 + j
        flip = [True]
        guard = 0
        while eng.active.any():
            flip[0] = not flip[0]
            emitted, finished = eng.step(speculate=flip[0])
            for slot, toks in emitted.items():
                out[s2r[slot]].extend(toks)
            for slot in finished:
                del s2r[slot]
            guard += 1
            assert guard < 400
        got = np.asarray([out[i] for i in range(4)])
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_mixed_greedy_sampled_wave(self, net, prompts):
        """Sampled slots ride the speculative dispatch at depth 1 and
        keep the fold_in(key, t) stream EXACTLY as they would without
        speculation; greedy slots stay bit-equal to generate()."""
        key = np.asarray([7, 11], np.uint32)
        # the sampled reference: same request alone on a spec-free
        # engine (the batch-composition-independence contract)
        ref_eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                    block_len=BL)
        s2r, ref_out = admit_all(ref_eng, [dict(
            prompt_ids=prompts[0], n_tokens=20, temperature=0.9,
            rng=key)])
        drain(ref_eng, s2r, ref_out)
        greedy_ref = generate(net, prompts[1:3], 20, temperature=0)

        eng = PagedDecodeEngine(net, n_slots=3, n_blocks=48,
                                block_len=BL, speculative=4)
        s2r, out = admit_all(eng, [
            dict(prompt_ids=prompts[0], n_tokens=20, temperature=0.9,
                 rng=key),
            dict(prompt_ids=prompts[1], n_tokens=20),
            dict(prompt_ids=prompts[2], n_tokens=20)])
        drain(eng, s2r, out)
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(ref_out[0]))
        np.testing.assert_array_equal(np.asarray(out[1]), greedy_ref[0])
        np.testing.assert_array_equal(np.asarray(out[2]), greedy_ref[1])

    def test_preempt_requeue_continuation_under_speculation(self, net):
        """Pool pressure during a speculative grow preempts the
        lowest-progress slot; its requeued continuation must still be
        bit-equal (server-level — the scheduler owns requeue)."""
        rng = np.random.default_rng(11)
        ps = [rng.integers(0, V, 4) for _ in range(4)]
        refs = [generate(net, p[None], 16, temperature=0)[0] for p in ps]
        # pool sized so 4 growing streams cannot all finish resident
        srv = GenerationServer(net, n_slots=4, n_blocks=9, block_len=BL,
                               speculative=4)
        srv.warmup(4, 16).start()
        streams = [srv.generate_async(p, 16) for p in ps]
        res = [s.result(timeout=300) for s in streams]
        srv.stop()
        for r, want in zip(res, refs):
            np.testing.assert_array_equal(np.asarray(r, np.int64),
                                          np.asarray(want, np.int64))

    def test_spec_depth_respects_remaining(self, net, prompts):
        """A slot 1 token from completion takes a depth-1 dispatch —
        never emits past n_tokens."""
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                block_len=BL, speculative=4)
        s2r, out = admit_all(eng, [dict(prompt_ids=prompts[0],
                                        n_tokens=2)])
        drain(eng, s2r, out)
        assert len(out[0]) == 2
        ref = generate(net, prompts[:1], 2, temperature=0)
        np.testing.assert_array_equal(np.asarray([out[0]]), ref)


# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def shared_prefix():
    return np.random.default_rng(21).integers(0, V, 6)   # 6 % BL != 0


class TestSharedPrefixCoW:
    def _noise(self, eng, seed=9):
        key = np.random.default_rng(seed)
        eng.pool.kv = tuple(
            (k + jnp.asarray(key.standard_normal(k.shape), k.dtype),
             v + jnp.asarray(key.standard_normal(v.shape), v.dtype))
            for k, v in eng.pool.kv)

    def test_shared_streams_bit_equal_private_noise_pool(
            self, net, shared_prefix):
        """Suffix lengths {0, 1, 3, 5} (exact match, sub-block, and
        multi-block extension) through a NOISE-filled pool: every
        stream bit-equal to its whole-batch generate() row, one prefix
        prefill for the whole set."""
        rng = np.random.default_rng(31)
        ps = [np.concatenate([shared_prefix, rng.integers(0, V, k)])
              for k in (0, 1, 3, 5)]
        refs = [generate(net, p[None], 16, temperature=0)[0] for p in ps]
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=48,
                                block_len=BL)
        self._noise(eng)
        eng.register_prefix(shared_prefix)
        s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=16)
                                   for p in ps])
        drain(eng, s2r, out)
        for i, want in enumerate(refs):
            np.testing.assert_array_equal(np.asarray(out[i]),
                                          np.asarray(want))
        assert eng.prefix_hits_total == 4
        assert eng.prefix_tokens_saved_total == 4 * 6
        # prefix len 6, BL 4: every hit forks the mid-block tail
        assert eng.prefix_forks_total == 4

    def test_fork_at_boundary(self, net):
        """A prefix ending ON a block boundary shares cleanly — no
        fork at all; a mid-block prefix forks exactly once per hit."""
        rng = np.random.default_rng(33)
        aligned = rng.integers(0, V, 8)          # 8 % BL == 0
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32,
                                block_len=BL)
        eng.register_prefix(aligned)
        p = np.concatenate([aligned, rng.integers(0, V, 2)])
        ref = generate(net, p[None], 10, temperature=0)[0]
        s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=10)])
        drain(eng, s2r, out)
        np.testing.assert_array_equal(np.asarray(out[0]), ref)
        assert eng.prefix_forks_total == 0
        assert eng.prefix_hits_total == 1

    def test_evict_while_shared(self, net, shared_prefix):
        """Evicting a CoW stream mid-flight returns its references:
        the cache's pins survive, fresh blocks return to the pool, and
        the next admission reuses the prefix with full parity."""
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32,
                                block_len=BL)
        eng.register_prefix(shared_prefix)
        free0 = eng.pool.free_blocks
        rng = np.random.default_rng(35)
        p = np.concatenate([shared_prefix, rng.integers(0, V, 3)])
        admitted = eng.admit_many([dict(prompt_ids=p, n_tokens=16)])
        eng.step()
        eng.evict(admitted[0][0])
        assert eng.pool.free_blocks == free0
        # shared blocks still granted to the cache (refcount 1 each)
        for b in eng._prefixes[tuple(int(t) for t in shared_prefix)][
                "blocks"]:
            assert eng.pool.allocator.refcount(b) == 1
        ref = generate(net, p[None], 16, temperature=0)[0]
        s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=16)])
        drain(eng, s2r, out)
        np.testing.assert_array_equal(np.asarray(out[0]), ref)

    def test_release_prefix_returns_blocks(self, net, shared_prefix):
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32,
                                block_len=BL)
        free0 = eng.pool.free_blocks
        key = eng.register_prefix(shared_prefix)
        assert eng.pool.free_blocks == free0 - 2     # ceil(6/4)
        assert eng.prefix_pinned_blocks == 2
        eng.release_prefix(key)
        assert eng.pool.free_blocks == free0
        assert eng.prefix_pinned_blocks == 0

    def test_sampled_cow_stream_matches_private(self, net,
                                                shared_prefix):
        """Sampling over a shared prefix: same request key, same
        fold_in(key, t) chain, bit-equal to the private-block stream
        (probs equality is what the CoW contract guarantees; the
        sampling tail is shared code)."""
        key = np.asarray([3, 19], np.uint32)
        rng = np.random.default_rng(37)
        p = np.concatenate([shared_prefix, rng.integers(0, V, 2)])
        ref_eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                    block_len=BL)
        s2r, ref_out = admit_all(ref_eng, [dict(
            prompt_ids=p, n_tokens=14, temperature=0.8, rng=key)])
        drain(ref_eng, s2r, ref_out)
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                block_len=BL)
        eng.register_prefix(shared_prefix)
        s2r, out = admit_all(eng, [dict(
            prompt_ids=p, n_tokens=14, temperature=0.8, rng=key)])
        drain(eng, s2r, out)
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(ref_out[0]))
        assert eng.prefix_hits_total == 1

    def test_exact_match_sampled(self, net, shared_prefix):
        """Prompt == prefix exactly: the first token comes from the
        REGISTRATION-cached distribution (no forward at all) and must
        still match the private stream — greedy and sampled."""
        key = np.asarray([5, 23], np.uint32)
        for kw in (dict(), dict(temperature=0.7, rng=key)):
            ref_eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                        block_len=BL)
            s2r, ref_out = admit_all(ref_eng, [dict(
                prompt_ids=shared_prefix, n_tokens=12, **kw)])
            drain(ref_eng, s2r, ref_out)
            eng = PagedDecodeEngine(net, n_slots=1, n_blocks=16,
                                    block_len=BL)
            eng.register_prefix(shared_prefix)
            s2r, out = admit_all(eng, [dict(
                prompt_ids=shared_prefix, n_tokens=12, **kw)])
            drain(eng, s2r, out)
            np.testing.assert_array_equal(np.asarray(out[0]),
                                          np.asarray(ref_out[0]))

    def test_preempt_requeue_cow_slot(self, net, shared_prefix):
        """A CoW slot preempted under pool pressure requeues as a
        continuation, re-matches the prefix on re-admission, and
        completes bit-equal (server-level requeue)."""
        rng = np.random.default_rng(39)
        ps = [np.concatenate([shared_prefix, rng.integers(0, V, 2)])
              for _ in range(3)]
        refs = [generate(net, p[None], 16, temperature=0)[0] for p in ps]
        # prefix pins 2 blocks; 3 growing streams over 8 usable fresh
        # blocks force preemption before all finish
        srv = GenerationServer(net, n_slots=3, n_blocks=11, block_len=BL)
        srv.register_prefix(shared_prefix)
        srv.warmup(8, 16).start()
        streams = [srv.generate_async(p, 16) for p in ps]
        res = [s.result(timeout=300) for s in streams]
        assert srv.engine.evict_requeue_total > 0, \
            "pool never pressured — the test lost its point"
        srv.stop()
        for r, want in zip(res, refs):
            np.testing.assert_array_equal(np.asarray(r, np.int64),
                                          np.asarray(want, np.int64))
        assert srv.engine.prefix_hits_total >= 3   # requeues re-hit

    def test_budget_check_is_prefix_aware(self, net, shared_prefix):
        """A request whose total footprint exceeds the unpinned pool is
        only admittable RIDING the prefix — check_budget must pass it
        with the prompt and reject the same lengths without."""
        # 8 total blocks usable; prefix pins 2 -> 6 unpinned; a
        # 28-token request needs 7 blocks alone but only 6 fresh ones
        # when 6 of its tokens ride the shared prefix
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=9,
                                block_len=BL)
        eng.register_prefix(shared_prefix)
        p = np.concatenate([shared_prefix,
                            np.random.default_rng(41).integers(0, V, 2)])
        eng.check_budget(8, 20, prompt_ids=p)        # rides the prefix
        with pytest.raises(ValueError, match="pinned"):
            eng.check_budget(8, 20)                  # judged by length

    def test_decode_time_fork_safety_net(self, net, shared_prefix):
        """The pre-dispatch fork pass is the INVARIANT's enforcement
        point, not just an admission optimization: hand a decoding
        slot a shared frontier block and the next step must fork it
        rather than write through the sharing."""
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=24,
                                block_len=BL)
        p = np.random.default_rng(43).integers(0, V, 4)
        admitted = eng.admit_many([dict(prompt_ids=p, n_tokens=12)])
        slot = admitted[0][0]
        eng.step()    # grow into the write block, decode one token
        # artificially share the block the NEXT write lands in (as a
        # second holder would)
        frontier = eng.slots[slot].blocks[int(eng.pos[slot])
                                          // BL]
        eng.pool.allocator.share([frontier])
        forks0 = eng.prefix_forks_total
        eng.step()
        assert eng.prefix_forks_total == forks0 + 1
        assert eng.slots[slot].blocks[-1] != frontier
        assert eng.pool.allocator.refcount(frontier) == 1
        eng.pool.allocator.free([frontier])          # drop our handle

    def test_register_prefix_capacity_errors(self, net):
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=4,
                                block_len=BL)
        with pytest.raises(ValueError, match="pool cannot host"):
            eng.register_prefix(np.zeros(16, np.int32))
        with pytest.raises(ValueError, match="no room to generate"):
            eng.register_prefix(np.zeros(MAXLEN, np.int32))


# --------------------------------------------------------------------------
class TestSpecCoWComposition:
    def test_speculative_over_shared_prefix(self, net, shared_prefix):
        """Both levers at once: drafts scored over CoW-mapped blocks,
        still bit-equal to generate()."""
        rng = np.random.default_rng(45)
        ps = [np.concatenate([shared_prefix, rng.integers(0, V, k)])
              for k in (0, 2, 4)]
        refs = [generate(net, p[None], 16, temperature=0)[0] for p in ps]
        srv = GenerationServer(net, n_slots=3, n_blocks=48,
                               block_len=BL, speculative=4)
        srv.register_prefix(shared_prefix)
        srv.warmup(10, 16).start()
        streams = [srv.generate_async(p, 16) for p in ps]
        res = [s.result(timeout=300) for s in streams]
        srv.stop()
        for r, want in zip(res, refs):
            np.testing.assert_array_equal(np.asarray(r, np.int64),
                                          np.asarray(want, np.int64))
        assert srv.engine.prefix_hits_total == 3
        assert srv.engine.spec_dispatches_total > 0


# --------------------------------------------------------------------------
class TestSchedulerSpecPolicy:
    def _srv(self, net):
        return GenerationServer(net, n_slots=2, n_blocks=32,
                                block_len=BL, speculative=4,
                                spec_accept_floor=0.5,
                                spec_probe_every=3)

    def test_auto_disable_and_probe_reenable(self, net):
        """Feed the EWMA by hand through the engine counters: a bad
        acceptance run latches drafting off; probes keep sampling the
        workload and a good run re-enables."""
        srv = self._srv(net)
        eng = srv.engine

        def dispatch(proposed, accepted, emitted):
            eng.spec_dispatches_total += 1
            eng.spec_proposed_total += proposed
            eng.spec_accepted_total += accepted
            eng.spec_emitted_total += emitted
            srv._spec_update(None)

        assert srv._spec_policy() is True
        for _ in range(12):
            dispatch(3, 0, 1)            # nothing accepted
        assert srv._spec_disabled
        # disabled: chunked dispatches except one probe every 3rd
        polls = [srv._spec_policy() for _ in range(6)]
        assert polls.count(True) == 2 and polls.count(False) == 4
        # probes with perfect acceptance recover the EWMA
        for _ in range(12):
            dispatch(3, 3, 4)
        assert not srv._spec_disabled
        assert srv._spec_policy() is True
        assert srv._spec_accept_ewma > 0.5

    def test_spec_gauges_live(self, net, prompts):
        from deeplearning4j_tpu import monitor
        monitor.enable(registry=monitor.MetricsRegistry())
        try:
            srv = GenerationServer(net, n_slots=2, n_blocks=32,
                                   block_len=BL, speculative=4)
            srv.warmup(5, 8).start()
            pref = np.random.default_rng(47).integers(0, V, 6)
            srv.register_prefix(pref)
            p = np.concatenate([pref, [1, 2]])
            srv.generate_async(p, 8).result(timeout=120)
            srv.generate_async(prompts[0], 8).result(timeout=120)
            srv.stop()
            reg = monitor.registry()
            assert reg.counter("serving_prefix_hits_total").value >= 1
            assert reg.counter(
                "serving_prefix_tokens_saved_total").value >= 6
            # accept-rate gauge exists and carries a finite value
            assert reg.gauge(
                "serving_spec_accept_rate").value is not None
        finally:
            monitor.disable()


# --------------------------------------------------------------------------
class TestFleetPrefixReRegistration:
    def test_prefix_survives_swap(self, tmp_path):
        """A fleet-registered prefix re-applies to every successor —
        prefilled under the NEW weights, so post-swap streams keep
        version-tagged parity AND the prefix hit path."""
        from deeplearning4j_tpu.serving import FleetServer, ModelRegistry

        v1, v2 = tiny_lm(seed=50), tiny_lm(seed=51)
        registry = ModelRegistry(tmp_path / "reg")
        registry.publish("lm", v1)
        fleet = FleetServer(registry)
        pref = np.random.default_rng(49).integers(0, V, 6)
        fleet.register_prefix("lm", pref)
        fleet.deploy("lm", n_slots=2, n_blocks=32, block_len=BL)
        p = np.concatenate([pref, [3, 4]])
        try:
            ref1 = generate(v1, p[None], 8, temperature=0)[0]
            s = fleet.server("lm").generate_async(p, 8)
            np.testing.assert_array_equal(
                np.asarray(s.result(timeout=120), np.int64), ref1)
            assert fleet.server("lm").engine.prefix_hits_total == 1
            registry.publish("lm", v2)
            fleet.swap("lm")
            ref2 = generate(v2, p[None], 8, temperature=0)[0]
            s = fleet.server("lm").generate_async(p, 8)
            np.testing.assert_array_equal(
                np.asarray(s.result(timeout=120), np.int64), ref2)
            # the successor re-registered and re-prefilled the prefix
            assert fleet.server("lm").engine.prefix_hits_total == 1
        finally:
            fleet.stop()
