"""Per-example prediction metadata + serializable curves (reference
`eval/meta/Prediction.java`, `eval/curves/`)."""

import numpy as np

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.eval import (
    Evaluation,
    Histogram,
    PrecisionRecallCurve,
    ReliabilityDiagram,
    ROC,
    RocCurve,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class TestPredictionMetadata:
    def test_errors_traceable_to_records(self):
        e = Evaluation()
        labels = np.eye(3)[[0, 1, 2, 0]]
        preds = np.eye(3)[[0, 2, 2, 0]] * 0.9 + 0.03   # example 1 wrong
        meta = [f"file.csv:line{i}" for i in range(4)]
        e.eval(labels, preds, record_metadata=meta)
        errs = e.get_prediction_errors()
        assert len(errs) == 1
        assert errs[0].actual_class == 1
        assert errs[0].predicted_class == 2
        assert errs[0].record_metadata == "file.csv:line1"

    def test_cell_and_class_queries(self):
        e = Evaluation()
        labels = np.eye(2)[[0, 0, 1, 1, 1]]
        preds = np.eye(2)[[0, 1, 1, 0, 1]] * 0.8 + 0.1
        e.eval(labels, preds, record_metadata=list(range(5)))
        assert [p.record_metadata
                for p in e.get_predictions(0, 1)] == [1]
        assert len(e.get_predictions_by_actual_class(1)) == 3
        assert len(e.get_predictions_by_predicted_class(0)) == 2

    def test_no_metadata_no_tracking(self):
        e = Evaluation()
        e.eval(np.eye(2)[[0, 1]], np.eye(2)[[1, 0]] * 0.9 + 0.05)
        assert e.get_prediction_errors() == []

    def test_through_evaluate_with_dataset_metadata(self):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.05))
                .list()
                .layer(DenseLayer(n_in=2, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((40, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
        net.fit(x, y, epochs=40, batch_size=20)
        ds = DataSet(x, y, example_metadata=[f"rec{i}" for i in range(40)])
        e = net.evaluate(ListDataSetIterator([ds]))
        total_tracked = sum(len(e.get_predictions_by_actual_class(c))
                            for c in (0, 1))
        assert total_tracked == 40
        for p in e.get_prediction_errors():
            assert p.record_metadata.startswith("rec")


class TestCurves:
    def _roc(self):
        r = ROC()
        labels = np.array([0, 0, 1, 1, 1, 0, 1, 0])
        probs = np.array([0.1, 0.4, 0.35, 0.8, 0.7, 0.2, 0.9, 0.6])
        r.eval(labels, probs)
        return r, labels, probs

    def test_roc_curve_object_roundtrip(self):
        r, _, _ = self._roc()
        curve = r.get_roc_curve_object()
        assert isinstance(curve, RocCurve)
        assert curve.num_points() == 9
        assert abs(curve.calculate_auc() - r.calculate_auc()) < 1e-9
        clone = RocCurve.from_json(curve.to_json())
        assert clone == curve
        assert clone.get_true_positive_rate(curve.num_points() - 1) == 1.0

    def test_precision_recall_curve_and_points(self):
        r, _, _ = self._roc()
        pr = r.get_precision_recall_curve()
        assert isinstance(pr, PrecisionRecallCurve)
        # highest-scored example is positive → precision 1 at recall 1/4
        t, p, rec = pr.get_point_at_recall(0.25)
        assert p == 1.0
        t, p, rec = pr.get_point_at_precision(0.7)
        assert p >= 0.7
        clone = PrecisionRecallCurve.from_json(pr.to_json())
        assert clone == pr
        assert abs(clone.calculate_auprc() - pr.calculate_auprc()) < 1e-12

    def test_histogram_and_reliability_serde(self):
        h = Histogram("residuals", -1.0, 1.0, [1, 5, 9, 5, 1])
        h2 = Histogram.from_json(h.to_json())
        assert h2 == h
        assert h2.num_bins() == 5
        assert len(h2.bin_edges()) == 6
        rd = ReliabilityDiagram("calib", [0.1, 0.5, 0.9], [0.15, 0.48, 0.88])
        rd2 = ReliabilityDiagram.from_json(rd.to_json())
        assert rd2 == rd and rd2.num_points() == 3


def test_metadata_survives_batching_and_shuffle():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1, 0, 1]]
    ds = DataSet(x, y, example_metadata=[f"r{i}" for i in range(6)])
    a, b = ds.split_test_and_train(4)
    assert a.example_metadata == ["r0", "r1", "r2", "r3"]
    assert b.example_metadata == ["r4", "r5"]
    batches = ds.batch_by(4)
    assert batches[1].example_metadata == ["r4", "r5"]
    ds.shuffle(seed=0)
    # metadata rides the same permutation as features
    for i in range(6):
        assert ds.example_metadata[i] == f"r{int(ds.features[i, 0]) // 2}"


def test_misaligned_metadata_raises():
    import pytest
    e = Evaluation()
    labels = np.eye(2)[[0, 1, 0]]
    preds = np.eye(2)[[0, 1, 1]] * 0.9 + 0.05
    with pytest.raises(ValueError):
        e.eval(labels, preds, record_metadata=["only-one"])


def test_calibration_returns_curve_objects():
    from deeplearning4j_tpu.eval import EvaluationCalibration
    rng = np.random.default_rng(0)
    probs = rng.random((200, 1))
    labels = (rng.random((200, 1)) < probs).astype(np.float64)
    ec = EvaluationCalibration()
    ec.eval(np.hstack([1 - labels, labels]), np.hstack([1 - probs, probs]))
    rd = ec.get_reliability_diagram(1)
    assert rd.num_points() == 10
    rd2 = ReliabilityDiagram.from_json(rd.to_json())
    assert rd2 == rd
    h = ec.get_probability_histogram(1)
    assert int(h.bin_counts.sum()) == 200


def test_pr_point_at_threshold_never_below_requested():
    pr = PrecisionRecallCurve([0.9, 0.5, 0.1], [0.9, 0.66, 0.4],
                              [0.2, 0.5, 1.0])
    t, p, r = pr.get_point_at_threshold(0.6)
    assert t == 0.9          # smallest stored threshold >= 0.6
    t, p, r = pr.get_point_at_threshold(0.95)
    assert t == 0.9          # none qualify -> highest stored


def test_probability_histogram_is_a_snapshot():
    from deeplearning4j_tpu.eval import EvaluationCalibration
    ec = EvaluationCalibration()
    probs = np.array([[0.2, 0.8], [0.7, 0.3]])
    labels = np.array([[0.0, 1.0], [1.0, 0.0]])
    ec.eval(labels, probs)
    h = ec.get_probability_histogram(1)
    before = h.bin_counts.copy()
    ec.eval(labels, probs)
    np.testing.assert_array_equal(h.bin_counts, before)


def test_calibration_residual_plot():
    from deeplearning4j_tpu.eval import EvaluationCalibration
    ec = EvaluationCalibration()
    probs = np.array([[0.1, 0.9], [0.8, 0.2]])
    labels = np.array([[0.0, 1.0], [1.0, 0.0]])  # both well-calibrated
    ec.eval(labels, probs)
    h = ec.get_residual_plot(1)
    assert int(h.bin_counts.sum()) == 2
    # residuals are 0.1 and 0.2 -> low bins populated
    assert h.bin_counts[:3].sum() == 2


def test_calibration_respects_2d_mask():
    from deeplearning4j_tpu.eval import EvaluationCalibration
    ec = EvaluationCalibration()
    probs = np.array([[0.1, 0.9], [0.8, 0.2], [0.5, 0.5]])
    labels = np.array([[0.0, 1.0], [1.0, 0.0], [1.0, 0.0]])
    ec.eval(labels, probs, mask=np.array([1, 1, 0]))
    assert int(ec.get_probability_histogram(1).bin_counts.sum()) == 2
