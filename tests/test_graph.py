"""ComputationGraph tests: vertices, topo sort, multi-input/output,
serde — mirrors the reference TestComputationGraphNetwork."""

import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.datasets.multidataset import MultiDataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import (
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    MergeVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
    vertex_from_dict,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, LSTM, OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.gradientcheck import check_gradients_fn


def simple_graph_conf():
    g = ComputationGraphConfiguration.graph_builder(
        NeuralNetConfiguration.builder().seed(42).updater(Adam(0.02)))
    g.add_inputs("in")
    g.add_layer("dense", DenseLayer(n_in=4, n_out=16, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss="mcxent"), "dense")
    g.set_outputs("out")
    return g.build()


class TestVertices:
    def test_elementwise_ops(self):
        a, b = jnp.ones((2, 3)), 2 * jnp.ones((2, 3))
        assert float(ElementWiseVertex(op="add").forward([a, b])[0, 0]) == 3
        assert float(ElementWiseVertex(op="subtract").forward([a, b])[0, 0]) == -1
        assert float(ElementWiseVertex(op="product").forward([a, b])[0, 0]) == 2
        assert float(ElementWiseVertex(op="average").forward([a, b])[0, 0]) == 1.5
        assert float(ElementWiseVertex(op="max").forward([a, b])[0, 0]) == 2

    def test_merge_subset(self):
        a = jnp.ones((2, 3))
        b = jnp.zeros((2, 2))
        m = MergeVertex().forward([a, b])
        assert m.shape == (2, 5)
        s = SubsetVertex(from_idx=1, to_idx=3).forward([m])
        assert s.shape == (2, 3)

    def test_l2_vertices(self):
        a = jnp.array([[3.0, 4.0]])
        b = jnp.zeros((1, 2))
        np.testing.assert_allclose(L2Vertex().forward([a, b]), [[5.0]], rtol=1e-5)
        n = L2NormalizeVertex().forward([a])
        np.testing.assert_allclose(n, [[0.6, 0.8]], rtol=1e-5)

    def test_scale_shift_reshape(self):
        x = jnp.ones((2, 6))
        np.testing.assert_allclose(ScaleVertex(scale_factor=3.0).forward([x]),
                                   3 * np.ones((2, 6)))
        np.testing.assert_allclose(ShiftVertex(shift_factor=1.0).forward([x]),
                                   2 * np.ones((2, 6)))
        r = ReshapeVertex(new_shape=[2, 3]).forward([x])
        assert r.shape == (2, 2, 3)

    def test_stack_unstack(self):
        a, b = jnp.ones((2, 3)), 2 * jnp.ones((2, 3))
        st = StackVertex().forward([a, b])
        assert st.shape == (4, 3)
        u0 = UnstackVertex(from_idx=0, stack_size=2).forward([st])
        u1 = UnstackVertex(from_idx=1, stack_size=2).forward([st])
        np.testing.assert_allclose(u0, a)
        np.testing.assert_allclose(u1, b)

    def test_rnn_vertices(self):
        x = jnp.arange(24.0).reshape(2, 4, 3)
        last = LastTimeStepVertex().forward([x], masks=[None])
        np.testing.assert_allclose(last, x[:, -1, :])
        ff = jnp.ones((2, 5))
        dup = DuplicateToTimeSeriesVertex().forward([ff, x])
        assert dup.shape == (2, 4, 5)

    def test_vertex_serde(self):
        for v in [ElementWiseVertex(op="max"), MergeVertex(),
                  SubsetVertex(from_idx=2, to_idx=5), ScaleVertex(scale_factor=2.0),
                  StackVertex(), UnstackVertex(from_idx=1, stack_size=3),
                  LastTimeStepVertex(), ReshapeVertex(new_shape=[3, 4])]:
            v2 = vertex_from_dict(v.to_dict())
            assert type(v2) is type(v)


class TestGraphContainer:
    def test_topo_sort_and_fit_iris(self):
        x, y = load_iris()
        net = ComputationGraph(simple_graph_conf()).init()
        net.fit(x, y, epochs=30, batch_size=50)
        e = net.evaluate(
            __import__("deeplearning4j_tpu.datasets.iterator",
                       fromlist=["ArrayDataSetIterator"]).ArrayDataSetIterator(
                x, y, batch_size=150))
        assert e.accuracy() > 0.9

    def test_skip_connection_graph(self):
        """Residual-style add vertex."""
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)))
        g.add_inputs("in")
        g.add_layer("fc1", DenseLayer(n_in=4, n_out=4, activation="tanh"), "in")
        g.add_vertex("residual", ElementWiseVertex(op="add"), "fc1", "in")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                       loss="mcxent"), "residual")
        g.set_outputs("out")
        conf = g.build()
        net = ComputationGraph(conf).init()
        x = np.random.randn(6, 4).astype(np.float32)
        y = np.eye(2)[np.random.randint(0, 2, 6)].astype(np.float32)
        net.fit(x, y, epochs=5, batch_size=6)
        assert np.isfinite(net.score())
        assert net.output(x).shape == (6, 2)

    def test_multi_input_multi_output(self):
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01)))
        g.add_inputs("inA", "inB")
        g.add_vertex("merged", MergeVertex(), "inA", "inB")
        g.add_layer("shared", DenseLayer(n_in=7, n_out=8, activation="relu"), "merged")
        g.add_layer("outA", OutputLayer(n_in=8, n_out=2, activation="softmax",
                                        loss="mcxent"), "shared")
        g.add_layer("outB", OutputLayer(n_in=8, n_out=1, activation="identity",
                                        loss="mse"), "shared")
        g.set_outputs("outA", "outB")
        net = ComputationGraph(g.build()).init()
        xa = np.random.randn(5, 3).astype(np.float32)
        xb = np.random.randn(5, 4).astype(np.float32)
        ya = np.eye(2)[np.random.randint(0, 2, 5)].astype(np.float32)
        yb = np.random.randn(5, 1).astype(np.float32)
        mds = MultiDataSet(features=[xa, xb], labels=[ya, yb])
        net.fit(mds, epochs=3)
        oa, ob = net.output(xa, xb)
        assert oa.shape == (5, 2) and ob.shape == (5, 1)

    def test_rnn_graph_with_last_time_step(self):
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01)))
        g.add_inputs("seq")
        g.add_layer("lstm", LSTM(n_in=5, n_out=8), "seq")
        g.add_vertex("last", LastTimeStepVertex(), "lstm")
        g.add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                       loss="mcxent"), "last")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        x = np.random.randn(4, 7, 5).astype(np.float32)
        y = np.eye(3)[np.random.randint(0, 3, 4)].astype(np.float32)
        net.fit(x, y, epochs=3, batch_size=4)
        assert net.output(x).shape == (4, 3)

    def test_graph_conf_serde(self):
        conf = simple_graph_conf()
        js = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(js)
        assert conf2.to_json() == js
        n1 = ComputationGraph(conf).init()
        n2 = ComputationGraph(conf2).init()
        for k, v in n1.param_table().items():
            np.testing.assert_allclose(np.asarray(v), np.asarray(n2.param_table()[k]))

    def test_cycle_detection(self):
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder())
        g.add_inputs("in")
        g.add_layer("a", DenseLayer(n_in=2, n_out=2), "b")
        g.add_layer("b", DenseLayer(n_in=2, n_out=2), "a")
        g.add_layer("out", OutputLayer(n_in=2, n_out=2), "b")
        g.set_outputs("out")
        with pytest.raises(ValueError):
            g.build()

    def test_graph_gradients(self):
        """Gradient-check a graph with fan-out (epsilon summation at
        fan-out comes from autodiff — reference setVertexEpsilon)."""
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(5))
        g.add_inputs("in")
        g.add_layer("fc", DenseLayer(n_in=3, n_out=4, activation="tanh"), "in")
        g.add_vertex("doubled", ElementWiseVertex(op="add"), "fc", "fc")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                       loss="mcxent"), "doubled")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        x = np.random.default_rng(0).standard_normal((4, 3))
        y = np.eye(2)[np.random.default_rng(1).integers(0, 2, 4)]

        import jax
        from deeplearning4j_tpu.nd.dtype import DataTypePolicy
        net.dtype = DataTypePolicy(jnp.float64, jnp.float64, jnp.float64)

        def loss_fn(p):
            loss, _ = net._loss_fn(p, net.net_state, [jnp.asarray(x)], [jnp.asarray(y)],
                                   None, None, None, train=False)
            return loss

        ok, worst, fails = check_gradients_fn(loss_fn, net.params)
        assert ok, f"worst {worst} {fails[:3]}"


class TestGraphRnnParity:
    """ComputationGraph TBPTT / rnn_time_step / pretrain — MLN parity
    (reference ComputationGraph.java:863 fit w/ doTruncatedBPTT,
    rnnTimeStep, pretrain)."""

    def _rnn_graph(self, tbptt=False):
        from deeplearning4j_tpu.nn.conf.builder import BackpropType
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2)))
        g.add_inputs("seq")
        g.add_layer("lstm", LSTM(n_in=5, n_out=8), "seq")
        g.add_layer("out", RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                          loss="mcxent"), "lstm")
        g.set_outputs("out")
        if tbptt:
            g.backprop_type(BackpropType.TRUNCATED_BPTT, 4)
        return g.build()

    def test_graph_tbptt_fit(self):
        net = ComputationGraph(self._rnn_graph(tbptt=True)).init()
        x = np.random.randn(2, 12, 5).astype(np.float32)
        y = np.eye(3)[np.random.randint(0, 3, (2, 12))].astype(np.float32)
        net.fit(x, y, epochs=2, batch_size=2)
        assert np.isfinite(net.score())

    def test_graph_tbptt_learns(self):
        # the TBPTT path must actually reduce loss on a memorizable batch
        net = ComputationGraph(self._rnn_graph(tbptt=True)).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 5)).astype(np.float32)
        y = np.eye(3)[rng.integers(0, 3, (4, 8))].astype(np.float32)
        net.fit(x, y, epochs=1, batch_size=4)
        first = net.score()
        net.fit(x, y, epochs=10, batch_size=4)
        assert net.score() < first

    def test_graph_rnn_time_step_matches_full_forward(self):
        net = ComputationGraph(self._rnn_graph()).init()
        x = np.random.randn(2, 6, 5).astype(np.float32)
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        stream = []
        for t in range(6):
            stream.append(np.asarray(net.rnn_time_step(x[:, t, :])))
        stream = np.stack(stream, axis=1)
        np.testing.assert_allclose(full, stream, atol=1e-5)

    def test_graph_tbptt_gradcheck(self):
        """Gradient-check one TBPTT chunk's loss (carries stopped)."""
        import jax
        net = ComputationGraph(self._rnn_graph(tbptt=True)).init()
        from deeplearning4j_tpu.nd.dtype import DataTypePolicy
        net.dtype = DataTypePolicy(jnp.float64, jnp.float64, jnp.float64)
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 4, 5))
        y = np.eye(3)[rng.integers(0, 3, (2, 4))]
        def loss_fn(p):
            # carries built inside so they pick up float64 under enable_x64
            carries = {"lstm": net.conf.nodes["lstm"].layer.init_carry(
                2, jnp.float64)}
            stopped = jax.tree_util.tree_map(jax.lax.stop_gradient, carries)
            loss, _ = net._loss_fn(p, net.net_state, [jnp.asarray(x)],
                                   [jnp.asarray(y)], None, None, None,
                                   train=False, carries=stopped)
            return loss

        ok, worst, fails = check_gradients_fn(loss_fn, net.params)
        assert ok, f"worst {worst} {fails[:3]}"

    def test_graph_pretrain(self):
        from deeplearning4j_tpu.nn.layers import AutoEncoder
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(9).updater(Adam(1e-2)))
        g.add_inputs("in")
        g.add_layer("ae", AutoEncoder(n_in=6, n_out=4), "in")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                       loss="mcxent"), "ae")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((16, 6)).astype(np.float32)
        before = {k: np.asarray(v) for k, v in net.params["ae"].items()}
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.pretrain(DataSet(x, x), epochs=3, batch_size=8)
        changed = any(not np.allclose(before[k], np.asarray(net.params["ae"][k]))
                      for k in before)
        assert changed


class TestGraphStepsPerExecution:
    """CG fused scan drain must match per-step dispatch numerics."""

    def _trajectory(self, spe):
        import numpy as np
        from deeplearning4j_tpu.optimize.listeners import CollectScoresListener

        rng = np.random.default_rng(0)
        x = rng.standard_normal((60, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 60)]
        b = NeuralNetConfiguration.builder().seed(5).updater(Adam(0.02))
        g = ComputationGraphConfiguration.graph_builder(b)
        g.add_inputs("in")
        g.set_input_types(InputType.feed_forward(4))
        g.add_layer("d", DenseLayer(n_out=12, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        listener = CollectScoresListener()
        net.set_listeners(listener)
        net.fit(x, y, epochs=4, batch_size=20,
                steps_per_execution=spe)
        return [s for _, s in listener.scores]

    def test_fused_matches_per_step(self):
        import numpy as np
        ref = self._trajectory(1)
        fused = self._trajectory(3)
        assert len(ref) == len(fused) == 12
        np.testing.assert_allclose(fused, ref, rtol=2e-5, atol=1e-6)
