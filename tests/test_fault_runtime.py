"""Preemption-tolerant training runtime (deeplearning4j_tpu/fault/).

Acceptance surface: kill-at-step-k then resume reproduces the
uninterrupted run's params/updater state BIT-identically on CPU —
plain, fused multi-step, scan_layers stacks, and threshold
gradient-sharing (incl. residual/τ and drifted per-replica updater
state); a corrupted newest checkpoint degrades to the previous one with
a logged warning; retention GC honors keep-last/keep-every; elastic
resume re-shards per-replica leaves across a changed replica count.
"""

import math
import shutil
import tempfile

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu import fault, monitor
from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.iterator import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
)
from deeplearning4j_tpu.fault import state as fstate
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def build_net(depth: int = 1, width: int = 8, n_in: int = 8,
              n_out: int = 3, seed: int = 7):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(0.01)).list())
    b = b.layer(DenseLayer(n_in=n_in, n_out=width, activation="tanh"))
    for _ in range(depth - 1):
        b = b.layer(DenseLayer(n_in=width, n_out=width, activation="tanh"))
    conf = (b.layer(OutputLayer(n_in=width, n_out=n_out,
                                activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def make_data(n=48, n_in=8, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


def make_iter(x, y, batch=8, shuffle=True):
    return ArrayDataSetIterator(x, y, batch_size=batch, shuffle=shuffle,
                                seed=11)


def trees_bitwise(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.asarray(p).dtype == np.asarray(q).dtype
        and np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(la, lb))


@pytest.fixture
def tmpdir_():
    d = tempfile.mkdtemp(prefix="fault_test_")
    yield d
    shutil.rmtree(d, ignore_errors=True)


def interrupt_fit(net, iterator, *, kill_at, freq, ckpt_dir, epochs=2,
                  spe=1, trainer=None):
    """Train with checkpointing + scripted preemption; returns the
    checkpointer after the kill fired."""
    ck = fault.AsyncCheckpointer(ckpt_dir, keep_last=10)
    net.add_listener(fault.CheckpointListener(ck, frequency=freq,
                                              iterator=iterator))
    net.add_listener(fault.PreemptionListener(kill_at, mode="exception"))
    with pytest.raises(fault.SimulatedPreemption):
        if trainer is not None:
            trainer.fit(iterator, epochs=epochs, batch_size=8)
        else:
            net.fit(iterator, epochs=epochs, steps_per_execution=spe)
    ck.wait()
    assert ck.steps(), "no checkpoint committed before the kill"
    return ck


# ===================================================== state schema units
class TestStateSchema:
    def test_flatten_roundtrip_and_checksums(self):
        tree = {"params": {"0": {"W": np.arange(6.0).reshape(2, 3),
                                 "b": np.zeros(3)}},
                "updater_state": {"0": {"W": {"m": np.ones(2),
                                              "v": np.zeros(2)}}}}
        flat = fstate.flatten_arrays(tree)
        assert fstate.unflatten_arrays(flat).keys() == tree.keys()
        back = fstate.unflatten_arrays(flat)
        assert np.array_equal(back["params"]["0"]["W"],
                              tree["params"]["0"]["W"])
        crcs = fstate.checksum_flat(flat)
        fstate.verify_checksums(flat, crcs)      # clean: no raise
        flat2 = dict(flat)
        key = next(iter(flat2))
        flat2[key] = flat2[key] + 1.0
        with pytest.raises(fault.CheckpointCorruptError):
            fstate.verify_checksums(flat2, crcs)

    def test_reserved_separator_rejected(self):
        with pytest.raises(ValueError):
            fstate.flatten_arrays({"a\x1fb": np.zeros(2)})

    def test_capture_restore_roundtrip(self):
        net = build_net()
        x, y = make_data()
        net.fit(x, y, epochs=1, batch_size=16)
        state = fstate.capture_training_state(net)
        clone = build_net()
        fstate.restore_training_state(clone, state)
        assert trees_bitwise(net.params, clone.params)
        assert trees_bitwise(net.updater_state, clone.updater_state)
        assert clone.iteration_count == net.iteration_count
        assert clone.epoch_count == net.epoch_count

    def test_stateless_updater_slots_survive_restore(self):
        # Sgd's init_state is {} — flat npz keys cannot represent empty
        # dicts, so restore must rebuild the structure (deep-merge over
        # an initialized tree) or _apply_updates KeyErrors on resume
        from deeplearning4j_tpu.common.updaters import Sgd
        b = (NeuralNetConfiguration.builder().seed(7)
             .updater(Sgd(0.05)).list()
             .layer(DenseLayer(n_in=8, n_out=8, activation="tanh"))
             .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                loss="mcxent"))
             .set_input_type(InputType.feed_forward(8)))
        net = MultiLayerNetwork(b.build()).init()
        x, y = make_data()
        net.fit(x, y, epochs=1, batch_size=16)
        state = fstate.capture_training_state(net)
        clone = MultiLayerNetwork(b.build())
        fstate.restore_training_state(clone, state)
        assert clone.updater_state["0"]["W"] == {}
        clone.fit(x, y, epochs=1, batch_size=16)   # no KeyError
        assert trees_bitwise(net.params, state["arrays"]["params"])

    def test_reshard_replica_stack(self):
        tree = {"0": {"W": np.arange(24, dtype=np.float32).reshape(2, 3, 4)}}
        res = fstate.reshard_replica_stack(tree, 4, kind="residual")
        assert res["0"]["W"].shape == (4, 3, 4)
        # error-feedback mass (the replica SUM) is conserved
        assert np.isclose(res["0"]["W"].sum(dtype=np.float64),
                          tree["0"]["W"].sum(dtype=np.float64), rtol=1e-6)
        st = fstate.reshard_replica_stack(tree, 3, kind="state")
        assert st["0"]["W"].shape == (3, 3, 4)
        assert np.allclose(st["0"]["W"][0], tree["0"]["W"].mean(axis=0))
        ints = {"0": {"n": np.array([3, 5], dtype=np.int32)}}
        assert fstate.reshard_replica_stack(
            ints, 3, kind="state")["0"]["n"].tolist() == [3, 3, 3]


# ===================================================== checkpointer core
class TestAsyncCheckpointer:
    def _state(self, i):
        return {"arrays": {"params": {"0": {"W": np.full((4, 4), float(i),
                                                         np.float32)}}},
                "meta": {"iteration_count": i, "epoch_count": 0}}

    def test_atomic_commit_and_load(self, tmpdir_):
        ck = fault.AsyncCheckpointer(tmpdir_, async_write=False)
        ck.save(self._state(5), 5)
        assert ck.steps() == [5]
        got = ck.load()
        assert got["meta"]["iteration_count"] == 5
        assert np.array_equal(got["arrays"]["params"]["0"]["W"],
                              np.full((4, 4), 5.0, np.float32))
        # no tmp droppings after a clean commit
        import os
        assert not [e for e in os.listdir(tmpdir_)
                    if e.startswith(".tmp-")]

    def test_retention_keep_last_and_keep_every(self, tmpdir_):
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=2, keep_every=10,
                                     async_write=False)
        for s in (5, 10, 15, 20, 25):
            ck.save(self._state(s), s)
        # keep_last=2 -> {20, 25}; keep_every=10 -> {10, 20} stay forever
        assert ck.steps() == [10, 20, 25]

    def test_async_latest_wins_and_wait(self, tmpdir_):
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10)
        for s in (1, 2, 3, 4):
            ck.save(self._state(s), s)
        ck.wait()
        steps = ck.steps()
        assert steps and steps[-1] == 4   # newest always committed

    def test_metrics_surface(self, tmpdir_):
        reg = monitor.enable(registry=monitor.MetricsRegistry())
        try:
            ck = fault.AsyncCheckpointer(tmpdir_, async_write=False)
            ck.save(self._state(3), 3)
            fault.resume(tmpdir_, model=build_net())
            expo = reg.exposition()
            for name in ("checkpoint_write_seconds", "checkpoint_bytes_total",
                         "checkpoint_last_age_seconds", "checkpoint_last_step",
                         "restore_total"):
                assert name in expo, f"{name} missing from /metrics"
        finally:
            monitor.disable()


# ============================================== interrupt/resume parity
class TestInterruptResumeParity:
    def test_plain_per_step(self, tmpdir_):
        x, y = make_data()
        ref = build_net()
        ref.fit(make_iter(x, y), epochs=2)

        net = build_net()
        it = make_iter(x, y)
        interrupt_fit(net, it, kill_at=7, freq=3, ckpt_dir=tmpdir_)

        it2 = make_iter(x, y)
        net2, meta = fault.resume(tmpdir_, iterator=it2)
        assert net2.iteration_count == meta["iteration_count"]
        net2.fit(it2, epochs=2 - net2.epoch_count)
        assert net2.iteration_count == ref.iteration_count
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)

    def test_fused_multi_step_boundaries(self, tmpdir_):
        x, y = make_data()
        ref = build_net()
        ref.fit(make_iter(x, y), epochs=2, steps_per_execution=3)

        net = build_net()
        it = make_iter(x, y)
        # kill_at=8 is NOT a group boundary: the preemption must fire at
        # the fused boundary (9), and the checkpoint cadence must land
        # on boundaries only
        interrupt_fit(net, it, kill_at=8, freq=4, ckpt_dir=tmpdir_, spe=3)
        from deeplearning4j_tpu.fault.checkpointer import list_checkpoints
        assert all(s % 3 == 0 for s in list_checkpoints(tmpdir_)), \
            "checkpoint landed off a fused step boundary"

        it2 = make_iter(x, y)
        net2, _ = fault.resume(tmpdir_, iterator=it2)
        net2.fit(it2, epochs=2 - net2.epoch_count, steps_per_execution=3)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)

    def test_mixed_bf16_policy(self, tmpdir_):
        # mixed-precision contract: bf16 compute, fp32 master params /
        # updater state — checkpoints are layout-identical to fp32
        # training and resume must rebuild the SAME mixed program
        # (fault/state.py records the ACTIVE policy in meta, since it
        # may come from an arg/env, not the conf)
        from deeplearning4j_tpu.nd.dtype import mixed_bf16
        x, y = make_data()

        def build_mixed():
            net = build_net(depth=3)
            return MultiLayerNetwork(net.conf,
                                     dtype_policy=mixed_bf16()).init()

        ref = build_mixed()
        assert ref.dtype.is_mixed
        ref.fit(make_iter(x, y), epochs=2)
        for leaf in jax.tree_util.tree_leaves(ref.params):
            assert np.asarray(leaf).dtype == np.float32

        net = build_mixed()
        it = make_iter(x, y)
        interrupt_fit(net, it, kill_at=7, freq=3, ckpt_dir=tmpdir_)
        it2 = make_iter(x, y)
        net2, meta = fault.resume(tmpdir_, iterator=it2)
        assert meta.get("dtype_policy", {}).get("compute_dtype") == \
            "bfloat16"
        assert net2.dtype.is_mixed     # policy came from meta, not conf
        net2.fit(it2, epochs=2 - net2.epoch_count)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)

    def test_scan_layers_stack(self, tmpdir_):
        # deep homogeneous stack: params/updater ride the fit as ONE
        # ``stacked::`` entry inside jit, per-layer keys at the
        # checkpoint boundary — resume must be oblivious to packing
        x, y = make_data()
        assert build_net(depth=5).conf.scan_layers
        ref = build_net(depth=5)
        ref.fit(make_iter(x, y), epochs=2)

        net = build_net(depth=5)
        it = make_iter(x, y)
        interrupt_fit(net, it, kill_at=7, freq=3, ckpt_dir=tmpdir_)
        it2 = make_iter(x, y)
        net2, _ = fault.resume(tmpdir_, iterator=it2)
        assert all(not k.startswith("stacked::") for k in net2.params)
        net2.fit(it2, epochs=2 - net2.epoch_count)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)

    def test_threshold_gradient_sharing(self, tmpdir_):
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        x, y = make_data()
        mesh = device_mesh()
        ref = build_net()
        rtr = ParallelTrainer(ref, mesh, mode="sync",
                              gradient_sharing="threshold")
        rtr.fit(make_iter(x, y), epochs=2, batch_size=8)

        net = build_net()
        it = make_iter(x, y)
        tr = ParallelTrainer(net, mesh, mode="sync",
                             gradient_sharing="threshold")
        interrupt_fit(net, it, kill_at=7, freq=3, ckpt_dir=tmpdir_,
                      trainer=tr)

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = ParallelTrainer(net2, mesh, mode="sync",
                              gradient_sharing="threshold")
        tr2.resume(tmpdir_, iterator=it2)
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8)
        assert trees_bitwise(ref.params, net2.params)
        # per-replica updater drift and the error-feedback residual + τ
        # must survive the restart bit-exactly too
        assert trees_bitwise(ref.updater_state, net2.updater_state)
        assert trees_bitwise(rtr.threshold_residual(),
                             tr2.threshold_residual())
        assert trees_bitwise(rtr._thr_tau, tr2._thr_tau)

    def test_threshold_fused_multi_step(self, tmpdir_):
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        x, y = make_data()
        mesh = device_mesh()
        ref = build_net()
        ParallelTrainer(ref, mesh, mode="sync",
                        gradient_sharing="threshold").fit(
            make_iter(x, y), epochs=2, batch_size=8,
            steps_per_execution=3)

        net = build_net()
        it = make_iter(x, y)
        tr = ParallelTrainer(net, mesh, mode="sync",
                             gradient_sharing="threshold")
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10)
        net.add_listener(fault.CheckpointListener(ck, frequency=3,
                                                  iterator=it))
        net.add_listener(fault.PreemptionListener(8, mode="exception"))
        with pytest.raises(fault.SimulatedPreemption):
            tr.fit(it, epochs=2, batch_size=8, steps_per_execution=3)
        ck.wait()

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = ParallelTrainer(net2, mesh, mode="sync",
                              gradient_sharing="threshold")
        tr2.resume(tmpdir_, iterator=it2)
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8,
                steps_per_execution=3)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)


    def _rs_trainer(self, net, mode):
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.tensor import fsdp_param_specs
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        # min_shard_elems=1 so the tiny test net genuinely shards its
        # 8x8 W leaves over the 8-way mesh (the output head's n_out=3
        # is indivisible and stays replicated — a mixed plan)
        specs = fsdp_param_specs(net, axis_size=8, min_shard_elems=1)
        return ParallelTrainer(net, device_mesh(), mode="sync",
                               gradient_sharing=mode,
                               rs_param_specs=specs)

    @pytest.mark.parametrize("mode,spe", [("dense_rs", 1),
                                          ("dense_rs", 3),
                                          ("threshold_rs", 1),
                                          ("threshold_rs", 3)])
    def test_rs_modes_interrupt_resume(self, tmpdir_, mode, spe):
        """ZeRO-sharded updater state must survive interrupt+resume
        BIT-exactly, per-step and fused: the checkpoint stores the
        reassembled FULL per-layer tree (replica-count independent) and
        the next fit re-slices it; threshold_rs additionally restores
        the per-bucket residual/τ."""
        x, y = make_data()
        ref = build_net()
        rtr = self._rs_trainer(ref, mode)
        rtr.fit(make_iter(x, y), epochs=2, batch_size=8,
                steps_per_execution=spe)

        net = build_net()
        it = make_iter(x, y)
        tr = self._rs_trainer(net, mode)
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10)
        net.add_listener(fault.CheckpointListener(ck, frequency=3,
                                                  iterator=it))
        net.add_listener(fault.PreemptionListener(7, mode="exception"))
        with pytest.raises(fault.SimulatedPreemption):
            tr.fit(it, epochs=2, batch_size=8, steps_per_execution=spe)
        ck.wait()
        assert ck.steps(), "no checkpoint before the kill"

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = self._rs_trainer(net2, mode)
        tr2.resume(tmpdir_, iterator=it2)
        # the restored updater tree is FULL per-layer (not sharded)
        assert net2.updater_state["0"]["W"]["m"].shape == \
            net2.params["0"]["W"].shape
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8,
                steps_per_execution=spe)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)
        if mode == "threshold_rs":
            assert trees_bitwise(rtr.threshold_residual(),
                                 tr2.threshold_residual())
            assert trees_bitwise(rtr._thr_tau, tr2._thr_tau)

    def test_scalar_tau_checkpoint_restores_into_bucketed(self, tmpdir_):
        """A PR-4 checkpoint carries ONE τ scalar; restoring it into
        the (default) bucketed trainer must broadcast it per bucket and
        keep training — and a bucketed tree checkpoint must coerce to a
        scalar for a bucketed=False trainer."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        x, y = make_data()
        net = build_net()
        it = make_iter(x, y)
        tr = ParallelTrainer(net, device_mesh(), mode="sync",
                             gradient_sharing="threshold", bucketed=False)
        ck = fault.AsyncCheckpointer(tmpdir_, async_write=False)
        net.add_listener(fault.CheckpointListener(ck, frequency=3,
                                                  iterator=it))
        tr.fit(it, epochs=1, batch_size=8)
        saved_tau = float(np.asarray(tr._thr_tau))

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = ParallelTrainer(net2, device_mesh(), mode="sync",
                              gradient_sharing="threshold")  # bucketed
        tr2.resume(tmpdir_, iterator=it2)
        tr2.fit(it2, epochs=1, batch_size=8)
        assert isinstance(tr2._thr_tau, dict)
        # coercion unit: tree -> scalar and scalar -> tree
        tree = gs.coerce_tau(np.float32(saved_tau), net.params.keys())
        assert set(tree) == set(net.params.keys())
        assert gs.tau_scalar(tree) == pytest.approx(saved_tau)

    def test_epoch_end_checkpoint_resumes_exact(self, tmpdir_):
        # epoch-cadence checkpoints pair epoch_count=e+1 with a cursor
        # normalized to the NEXT pass — an un-normalized end-of-pass
        # cursor would replay an empty pass and train one epoch short
        x, y = make_data()
        ref = build_net()
        ref.fit(make_iter(x, y), epochs=3)

        net = build_net()
        it = make_iter(x, y)
        ck = fault.AsyncCheckpointer(tmpdir_, async_write=False)
        net.add_listener(fault.CheckpointListener(
            ck, frequency=10 ** 9, epoch_frequency=1, iterator=it))
        net.fit(it, epochs=1)

        it2 = make_iter(x, y)
        net2, meta = fault.resume(tmpdir_, iterator=it2)
        assert meta["epoch_count"] == 1
        assert meta["iterator"] == {"epoch": 1, "batch": 0, "seed": 11,
                                    "shuffle": True}
        net2.fit(it2, epochs=3 - net2.epoch_count)
        assert net2.iteration_count == ref.iteration_count == 18
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)

    def test_trainer_fires_epoch_and_fit_events(self):
        # the parallel trainers must dispatch epoch/fit listener events
        # like the containers do — CheckpointListener's end-of-fit
        # durability drain and epoch-cadence saves depend on them
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        events = []

        class Probe(TrainingListener):
            def on_fit_start(self, model):
                events.append("fit_start")

            def on_epoch_start(self, model, epoch):
                events.append(("epoch_start", epoch))

            def on_epoch_end(self, model, epoch):
                events.append(("epoch_end", epoch))

            def on_fit_end(self, model):
                events.append("fit_end")

        x, y = make_data()
        net = build_net()
        net.add_listener(Probe())
        ParallelTrainer(net, device_mesh(), mode="sync").fit(
            make_iter(x, y), epochs=2, batch_size=8)
        assert events == ["fit_start", ("epoch_start", 0), ("epoch_end", 0),
                          ("epoch_start", 1), ("epoch_end", 1), "fit_end"]

    def test_computation_graph(self, tmpdir_):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph,
            ComputationGraphConfiguration,
        )

        def build_graph():
            g = ComputationGraphConfiguration.graph_builder(
                NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(0.01)))
            g.add_inputs("in")
            g.add_layer("d1", DenseLayer(n_in=8, n_out=10,
                                         activation="tanh"), "in")
            g.add_layer("out", OutputLayer(n_in=10, n_out=3,
                                           activation="softmax",
                                           loss="mcxent"), "d1")
            g.set_outputs("out")
            return ComputationGraph(g.build()).init()

        x, y = make_data()
        ref = build_graph()
        ref.fit(make_iter(x, y), epochs=2)

        g = build_graph()
        it = make_iter(x, y)
        interrupt_fit(g, it, kill_at=7, freq=3, ckpt_dir=tmpdir_)
        it2 = make_iter(x, y)
        g2, _ = fault.resume(tmpdir_, iterator=it2)
        assert isinstance(g2, ComputationGraph)   # rebuilt from meta
        g2.fit(it2, epochs=2 - g2.epoch_count)
        assert trees_bitwise(ref.params, g2.params)
        assert trees_bitwise(ref.updater_state, g2.updater_state)

    def test_pipeline_parallel_trainer(self, tmpdir_):
        from deeplearning4j_tpu.parallel.pipeline_container import (
            PipelineParallelTrainer,
        )

        def build_deep():
            # n_in=4 prolog layer differs from the 8-wide body, so the
            # homogeneous run is the 4 inner blocks (divisible into 2
            # stages)
            return build_net(depth=5, n_in=4)

        x, y = make_data(n_in=4)
        mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
        ref = build_deep()
        PipelineParallelTrainer(ref, mesh, microbatches=2).fit(
            make_iter(x, y), epochs=2, batch_size=8)

        net = build_deep()
        it = make_iter(x, y)
        tr = PipelineParallelTrainer(net, mesh, microbatches=2)
        interrupt_fit(net, it, kill_at=7, freq=3, ckpt_dir=tmpdir_,
                      trainer=tr)
        net2 = build_deep()
        it2 = make_iter(x, y)
        tr2 = PipelineParallelTrainer(net2, mesh, microbatches=2)
        tr2.resume(tmpdir_, iterator=it2)
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8)
        assert trees_bitwise(ref.params, net2.params)
        assert trees_bitwise(ref.updater_state, net2.updater_state)


# ============================================= corrupt-shard fallback
class TestCorruptionFallback:
    def _checkpointed_run(self, tmpdir_):
        x, y = make_data()
        net = build_net()
        it = make_iter(x, y)
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10,
                                     async_write=False)
        net.add_listener(fault.CheckpointListener(ck, frequency=2,
                                                  iterator=it))
        net.fit(it, epochs=1)
        return ck.steps()

    def test_flip_falls_back_with_warning(self, tmpdir_, caplog):
        steps = self._checkpointed_run(tmpdir_)
        assert len(steps) >= 2
        fault.corrupt_checkpoint(tmpdir_, mode="flip")
        with caplog.at_level("WARNING", logger="deeplearning4j_tpu.fault"):
            _, meta = fault.resume(tmpdir_)
        assert meta["iteration_count"] == steps[-2]
        assert any("corrupt" in r.message for r in caplog.records)

    def test_truncate_falls_back(self, tmpdir_):
        steps = self._checkpointed_run(tmpdir_)
        fault.corrupt_checkpoint(tmpdir_, mode="truncate")
        _, meta = fault.resume(tmpdir_)
        assert meta["iteration_count"] == steps[-2]

    def test_manifest_corruption_falls_back(self, tmpdir_):
        steps = self._checkpointed_run(tmpdir_)
        fault.corrupt_checkpoint(tmpdir_, mode="truncate",
                                 target="manifest")
        _, meta = fault.resume(tmpdir_)
        assert meta["iteration_count"] == steps[-2]

    def test_all_corrupt_raises_typed_error(self, tmpdir_):
        steps = self._checkpointed_run(tmpdir_)
        for s in steps:
            fault.corrupt_checkpoint(tmpdir_, step=s, mode="flip")
        with pytest.raises(fault.CheckpointCorruptError):
            fault.resume(tmpdir_)

    def test_empty_dir_raises_filenotfound(self, tmpdir_):
        with pytest.raises(FileNotFoundError):
            fault.resume(tmpdir_)


# ==================================================== elastic resume
class TestElasticResume:
    def test_replica_count_change(self, tmpdir_):
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        x, y = make_data()
        m2 = Mesh(np.array(jax.devices()[:2]), ("data",))
        m4 = Mesh(np.array(jax.devices()[:4]), ("data",))
        net = build_net()
        it = make_iter(x, y)
        tr = ParallelTrainer(net, m2, mode="sync",
                             gradient_sharing="threshold")
        interrupt_fit(net, it, kill_at=6, freq=4, ckpt_dir=tmpdir_,
                      trainer=tr)
        saved = fault.load_latest_valid(tmpdir_)[0]
        saved_res = saved["arrays"]["trainer"]["residual_r"]
        assert fstate.stacked_replica_count(saved_res) == 2

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = ParallelTrainer(net2, m4, mode="sync",
                              gradient_sharing="threshold")
        tr2.resume(tmpdir_, iterator=it2)
        res4 = tr2.threshold_residual()
        assert fstate.stacked_replica_count(res4) == 4
        # error-feedback mass conserved through the re-shard
        s_old = sum(np.asarray(l).sum(dtype=np.float64)
                    for l in jax.tree_util.tree_leaves(saved_res))
        s_new = sum(np.asarray(l).sum(dtype=np.float64)
                    for l in jax.tree_util.tree_leaves(res4))
        assert np.isclose(s_old, s_new, rtol=1e-4, atol=1e-7)
        # and the elastic run trains to completion on the new mesh
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8)
        assert net2.iteration_count == 12

    def test_threshold_rs_replica_count_change(self, tmpdir_):
        """Elastic resume for the ZeRO mode: the sharded updater state
        checkpoints as the FULL per-layer tree, so a changed replica
        count just re-slices at the next fit; the per-replica residual
        re-shards sum-preserving and per-bucket τ carries over."""
        from deeplearning4j_tpu.parallel.tensor import fsdp_param_specs
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        x, y = make_data()
        m2 = Mesh(np.array(jax.devices()[:2]), ("data",))
        m4 = Mesh(np.array(jax.devices()[:4]), ("data",))
        net = build_net()
        it = make_iter(x, y)
        tr = ParallelTrainer(
            net, m2, mode="sync", gradient_sharing="threshold_rs",
            rs_param_specs=fsdp_param_specs(net, axis_size=2,
                                            min_shard_elems=1))
        interrupt_fit(net, it, kill_at=6, freq=4, ckpt_dir=tmpdir_,
                      trainer=tr)
        saved = fault.load_latest_valid(tmpdir_)[0]
        assert fstate.stacked_replica_count(
            saved["arrays"]["trainer"]["residual_r"]) == 2
        # the checkpointed updater tree is FULL-shape (not 2-sharded)
        assert saved["arrays"]["updater_state"]["0"]["W"]["m"].shape == \
            np.shape(net.params["0"]["W"])

        net2 = build_net()
        it2 = make_iter(x, y)
        tr2 = ParallelTrainer(
            net2, m4, mode="sync", gradient_sharing="threshold_rs",
            rs_param_specs=fsdp_param_specs(net2, axis_size=4,
                                            min_shard_elems=1))
        tr2.resume(tmpdir_, iterator=it2)
        assert fstate.stacked_replica_count(tr2.threshold_residual()) == 4
        assert isinstance(tr2._thr_tau, dict)
        tr2.fit(it2, epochs=2 - net2.epoch_count, batch_size=8)
        assert net2.iteration_count == 12


# ================================================== iterator cursor
class TestIteratorCursor:
    def test_array_iterator_mid_epoch(self):
        x, y = make_data(n=40)
        a = make_iter(x, y)
        seen = []
        for ep in range(2):
            for i, ds in enumerate(a):
                seen.append(np.asarray(ds.features))
                if ep == 1 and i == 1:
                    cur = a.cursor()
                    break
            else:
                continue
            break
        assert cur == {"epoch": 1, "batch": 2, "seed": 11, "shuffle": True}
        b = make_iter(x, y)
        b.seek(cur)
        nxt = next(iter(b))
        # the resumed stream continues with the batch AFTER the cursor,
        # under the SAME epoch-1 permutation
        expect_a = make_iter(x, y)
        it = iter(expect_a)
        for _ in range(5):
            next(it)           # drain epoch 0
        it = iter(expect_a)
        next(it), next(it)
        want = next(it)
        assert np.array_equal(np.asarray(nxt.features),
                              np.asarray(want.features))

    def test_seek_to_epoch_end_yields_nothing(self):
        x, y = make_data(n=40)
        a = make_iter(x, y)
        a.seek({"epoch": 0, "batch": 5, "seed": 11})
        assert list(a) == []
        assert len(list(a)) == 5   # next pass is a full epoch

    def test_async_counts_consumed_not_prefetched(self):
        x, y = make_data(n=64)
        base = make_iter(x, y)
        a = AsyncDataSetIterator(base, prefetch=4)
        it = iter(a)
        for _ in range(3):
            next(it)
        import time
        time.sleep(0.2)      # let the worker run far ahead
        cur = a.cursor()
        assert cur["batch"] == 3, cur   # consumer position, not producer
        it.close()
        b = AsyncDataSetIterator(make_iter(x, y), prefetch=4)
        b.seek(cur)
        got = next(iter(b))
        ref = make_iter(x, y)
        rit = iter(ref)
        for _ in range(3):
            next(rit)
        want = next(rit)
        assert np.array_equal(np.asarray(got.features),
                              np.asarray(want.features))

    def test_unseekable_iterator_clear_error(self):
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        with pytest.raises(NotImplementedError):
            ListDataSetIterator([]).seek({"epoch": 0, "batch": 0})


# ========================================= serializer hardening satellite
class TestSerializerHardening:
    def test_atomic_write_and_checksum_roundtrip(self, tmpdir_):
        import os
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        net = build_net()
        x, y = make_data()
        net.fit(x, y, epochs=1, batch_size=16)
        path = os.path.join(tmpdir_, "model.zip")
        ModelSerializer.write_model(net, path)
        assert not [e for e in os.listdir(tmpdir_) if e.startswith(".")]
        back = ModelSerializer.restore_model(path)
        assert trees_bitwise(net.params, back.params)

    def test_corrupt_zip_raises_typed_error(self, tmpdir_):
        import os
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        net = build_net()
        path = os.path.join(tmpdir_, "model.zip")
        ModelSerializer.write_model(net, path)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:     # silent bit rot mid-file
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(fault.CheckpointCorruptError):
            ModelSerializer.restore_model(path)

    def test_truncated_zip_raises_typed_error(self, tmpdir_):
        import os
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        net = build_net()
        path = os.path.join(tmpdir_, "model.zip")
        ModelSerializer.write_model(net, path)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        with pytest.raises(fault.CheckpointCorruptError):
            ModelSerializer.restore_model(path)


# ========================================= early stopping resume satellite
class TestEarlyStoppingResume:
    def test_persist_and_resume(self, tmpdir_):
        from deeplearning4j_tpu.earlystopping.conditions import (
            MaxEpochsTerminationCondition,
        )
        from deeplearning4j_tpu.earlystopping.config import (
            EarlyStoppingConfiguration,
        )
        from deeplearning4j_tpu.earlystopping.trainer import (
            EarlyStoppingTrainer,
        )

        x, y = make_data()

        def cfg(n):
            return EarlyStoppingConfiguration(
                epoch_termination_conditions=[
                    MaxEpochsTerminationCondition(n)])

        ref = EarlyStoppingTrainer(cfg(4), build_net(), make_iter(x, y)).fit()

        # phase 1: stop after 2 epochs, persisting through the fault
        # checkpointer; phase 2: fresh trainer resumes to 4 total
        t1 = EarlyStoppingTrainer(cfg(2), build_net(), make_iter(x, y),
                                  checkpointer=tmpdir_)
        r1 = t1.fit()
        assert r1.total_epochs == 2  # MaxEpochs(2) stops after epoch 1

        t2 = EarlyStoppingTrainer(cfg(4), build_net(), make_iter(x, y),
                                  checkpointer=tmpdir_)
        r2 = t2.fit(resume=True)
        assert set(r2.score_vs_epoch) == set(ref.score_vs_epoch)
        assert r2.best_model_epoch == ref.best_model_epoch
        assert np.isclose(r2.best_model_score, ref.best_model_score,
                          rtol=1e-6)
        assert r2.best_model is not None


# =============================================== step_boundary contract
class TestStepBoundaryContract:
    def test_fused_marks_only_group_tail(self):
        x, y = make_data()
        net = build_net()
        seen = []

        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        class Probe(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score,
                               **info):
                seen.append((iteration, info.get("step_boundary", True)))

        net.add_listener(Probe())
        net.fit(make_iter(x, y, shuffle=False), epochs=1,
                steps_per_execution=3)
        # 6 batches, spe=3 -> groups [0,1,2], [3,4,5]; boundaries at 2, 5
        assert [b for _, b in seen] == [False, False, True,
                                        False, False, True]
