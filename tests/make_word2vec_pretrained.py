"""Produce the packaged Word2Vec pretrained vectors.

Trains skip-gram embeddings on this repository's own documentation
(real English prose, fully reproducible from the repo — no download)
and writes them in the Google word2vec BINARY format via
`WordVectorSerializer` into `deeplearning4j_tpu/zoo/weights/` — the
third packaged pretrained artifact (after the LeNet and char-LM
checkpoints), playing the reference's hosted-word-vectors role
(`WordVectorSerializer.java` readers were pointed at GoogleNews-style
.bin files; here the packaged artifact exercises the exact same
serializer path).

Quality gate before overwrite: the mean cosine similarity over pairs
of terms that co-occur throughout the docs must beat the mean over
random vocabulary pairs by a clear margin — embeddings that never
learned co-occurrence structure fail the gate.

    python tests/make_word2vec_pretrained.py
"""

import hashlib
import json
import os
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parents[1]))

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

REPO = Path(__file__).parents[1]
WEIGHTS_DIR = REPO / "deeplearning4j_tpu" / "zoo" / "weights"
OUT_NAME = "word2vec_docs.bin"

# doc-domain terms that co-occur throughout the corpus vs random pairs
RELATED_PAIRS = [
    ("ring", "attention"), ("keras", "import"), ("mesh", "sharding"),
    ("gradient", "loss"), ("test", "suite"), ("layer", "network"),
]


def load_sentences():
    parts = []
    for p in [REPO / "README.md", *sorted((REPO / "docs").glob("*.md")),
              REPO / "SURVEY.md"]:
        parts.append(p.read_text(errors="ignore"))
    text = "\n".join(parts).lower()
    sents = []
    for line in text.splitlines():
        toks = re.findall(r"[a-z][a-z0-9_]+", line)
        if len(toks) >= 3:
            sents.append(toks)
    return sents


def quality_gate(w2v, rng):
    vocab_words = [w for w in w2v.vocab.words()
                   if w2v.vocab.word_frequency(w) >= 3]
    related = [w2v.similarity(a, b) for a, b in RELATED_PAIRS
               if a in vocab_words and b in vocab_words]
    assert len(related) >= 4, f"gate pairs missing from vocab: {related}"
    rand = [w2v.similarity(vocab_words[i], vocab_words[j])
            for i, j in zip(rng.integers(0, len(vocab_words), 200),
                            rng.integers(0, len(vocab_words), 200))
            if vocab_words[i] != vocab_words[j]]
    rel_mean, rand_mean = float(np.mean(related)), float(np.mean(rand))
    print(f"gate: related {rel_mean:.3f} vs random {rand_mean:.3f}")
    assert rel_mean > rand_mean + 0.15, \
        f"embeddings failed the co-occurrence gate ({rel_mean:.3f} vs " \
        f"{rand_mean:.3f})"
    return rel_mean, rand_mean


def main():
    from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents = load_sentences()
    n_words = sum(len(s) for s in sents)
    print(f"corpus: {len(sents)} sentences / {n_words} tokens")
    w2v = Word2Vec(layer_size=64, window_size=8, negative_sample=5,
                   min_word_frequency=3, epochs=40, batch_size=4096,
                   seed=1234)
    w2v.build_vocab(sents)
    w2v.fit(sents)
    rel_mean, rand_mean = quality_gate(w2v, np.random.default_rng(0))

    out = WEIGHTS_DIR / OUT_NAME
    WordVectorSerializer.write_binary(w2v, out)
    sha = hashlib.sha256(out.read_bytes()).hexdigest()
    manifest_path = WEIGHTS_DIR / "MANIFEST.json"
    manifest = json.loads(manifest_path.read_text())
    manifest[OUT_NAME] = {
        "sha256": sha,
        "format": "google word2vec binary",
        "vocab_words": w2v.vocab.num_words(),
        "vector_length": 64,
        "gate_related_mean_cos": round(rel_mean, 4),
        "gate_random_mean_cos": round(rand_mean, 4),
        "train_corpus": ("this repository's README/docs/SURVEY markdown, "
                         f"{n_words} tokens"),
        "generator": "tests/make_word2vec_pretrained.py",
    }
    manifest_path.write_text(json.dumps(manifest, indent=2))
    print(f"wrote {out} ({out.stat().st_size} bytes, sha256 {sha[:12]}…)")


if __name__ == "__main__":
    main()
