"""Bench-artifact resilience: the driver's scoreboard is the last JSON
line `python bench.py` prints, and it must NEVER read `value: 0.0`
while a committed chip measurement exists (round 4 lost its official
perf record to a tunnel flap at capture time exactly this way).

Role match: `PerformanceListener.java:87-88` — measurement tooling must
be at least as robust as the thing it measures.
"""

import json
import os

import pytest

from deeplearning4j_tpu import bench


@pytest.fixture
def lastgood(tmp_path, monkeypatch):
    path = tmp_path / "LASTGOOD_BENCH.json"
    monkeypatch.setenv("DL4J_BENCH_LASTGOOD", str(path))
    return path


def _fake_result(platform="tpu", value=1234.5):
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": value, "unit": "images/sec", "vs_baseline": value / 360.0,
        "platform": platform, "mfu": 0.31,
        "extras": {"lenet_mnist": {"images_per_sec": 9e4}},
    }


def test_emit_failure_falls_back_to_lastgood(lastgood, capsys):
    lastgood.write_text(json.dumps(_fake_result()))
    bench._emit_failure("tunnel unreachable after 4 probes", attempts=4)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 1234.5
    assert out["stale"] is True
    assert "tunnel unreachable" in out["stale_error"]
    assert out["probe_attempts"] == 4
    # the real throughput survives — the scoreboard is never zeroed
    assert out["vs_baseline"] > 0


def test_emit_failure_nonconnectivity_never_echoes_lastgood(lastgood, capsys):
    """An in-bench crash is a regression signal: even with a committed
    chip measurement available it must emit the explicit error/zero
    shape — a genuine regression must not surface as 2425 img/s with a
    `stale` flag (ADVICE r5)."""
    lastgood.write_text(json.dumps(_fake_result(value=2425.14)))
    bench._emit_failure("primary bench failed: ValueError: shapes differ",
                        attempts=0, connectivity=False)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert "stale" not in out
    assert "shapes differ" in out["error"]


def test_connectivity_classifier():
    assert bench._is_connectivity_error(ConnectionError("reset"))
    assert bench._is_connectivity_error(TimeoutError())
    assert bench._is_connectivity_error(
        RuntimeError("accelerator tunnel unreachable after 4 probes"))
    assert bench._is_connectivity_error(
        RuntimeError("DEADLINE_EXCEEDED: grpc channel"))
    assert not bench._is_connectivity_error(ValueError("shapes differ"))
    assert not bench._is_connectivity_error(KeyError("extras"))


def test_emit_failure_connectivity_still_echoes_lastgood(lastgood, capsys):
    lastgood.write_text(json.dumps(_fake_result()))
    bench._emit_failure("mid-run tunnel drop: connection reset",
                        attempts=1,
                        connectivity=bench._is_connectivity_error(
                            ConnectionError("connection reset")))
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 1234.5
    assert out["stale"] is True


def test_emit_failure_without_lastgood_is_explicit_zero(lastgood, capsys):
    assert not lastgood.exists()
    bench._emit_failure("no tunnel", attempts=2)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert "no tunnel" in out["error"]


def test_emit_failure_ignores_corrupt_lastgood(lastgood, capsys):
    lastgood.write_text("{not json")
    bench._emit_failure("err", attempts=1)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0


def test_emit_failure_ignores_zero_valued_lastgood(lastgood, capsys):
    lastgood.write_text(json.dumps(_fake_result(value=0.0)))
    bench._emit_failure("err", attempts=1)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # a zeroed artifact is not a measurement — fall through to the
    # explicit-error shape rather than laundering it as stale-good
    assert out["value"] == 0.0
    assert "error" in out


def test_save_lastgood_persists_accelerator_runs(lastgood):
    bench._save_lastgood(_fake_result(platform="tpu", value=2400.0))
    saved = json.loads(lastgood.read_text())
    assert saved["value"] == 2400.0
    assert "measured_at" in saved
    assert "stale" not in saved


def test_save_lastgood_refuses_cpu_sandbox_runs(lastgood):
    bench._save_lastgood(_fake_result(platform="cpu", value=50.0))
    assert not lastgood.exists()


def test_save_lastgood_refuses_zero_value(lastgood):
    bench._save_lastgood(_fake_result(platform="tpu", value=0.0))
    assert not lastgood.exists()


def test_save_then_emit_round_trip_strips_stale_markers(lastgood, capsys):
    bench._save_lastgood(_fake_result(value=2425.14))
    bench._emit_failure("flap", attempts=1)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 2425.14
    assert out["stale"] is True
    # a second save from a fresh run must not carry staleness forward
    bench._save_lastgood(out | {"value": 2500.0, "platform": "tpu"})
    saved = json.loads(lastgood.read_text())
    assert "stale" not in saved and "stale_error" not in saved
    assert saved["value"] == 2500.0


def test_committed_lastgood_artifact_is_valid():
    """The repo must always carry a usable committed fallback."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "LASTGOOD_BENCH.json")) as f:
        d = json.load(f)
    assert d["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert float(d["value"]) > 0
    assert d.get("platform") != "cpu"
    assert "measured_at" in d
