"""Tests: record readers, new fetchers, memory reports, ModelGuesser,
new listeners."""

import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam, Nesterovs
from deeplearning4j_tpu.datasets.fetchers import (
    Cifar10DataSetIterator,
    EmnistDataSetIterator,
)
from deeplearning4j_tpu.datasets.records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.memory import memory_report
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import (
    ParamAndGradientIterationListener,
    SleepyTrainingListener,
)
from deeplearning4j_tpu.util.guesser import ModelGuesser
from deeplearning4j_tpu.util.serializer import ModelSerializer


class TestRecordReaders:
    def test_csv_reader_and_iterator(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n")
        reader = CSVRecordReader(p)
        it = RecordReaderDataSetIterator(reader, batch_size=2,
                                         label_index=-1, num_classes=3)
        ds = next(iter(it))
        np.testing.assert_array_equal(ds.features, [[1, 2], [3, 4]])
        np.testing.assert_array_equal(ds.labels, [[1, 0, 0], [0, 1, 0]])
        ds2 = it.next()
        assert ds2.features.shape == (1, 2)

    def test_regression_mode(self):
        reader = CollectionRecordReader([[1.0, 2.0, 0.5], [3.0, 4.0, 1.5]])
        it = RecordReaderDataSetIterator(reader, 2, label_index=-1,
                                         regression=True)
        ds = it.next()
        np.testing.assert_array_equal(ds.labels, [[0.5], [1.5]])

    def test_sequence_reader_with_masking(self, tmp_path):
        # two sequences, lengths 3 and 2, label column last
        s1 = tmp_path / "s1.csv"
        s1.write_text("0.1,0.2,0\n0.3,0.4,1\n0.5,0.6,0\n")
        s2 = tmp_path / "s2.csv"
        s2.write_text("0.7,0.8,1\n0.9,1.0,1\n")
        reader = CSVSequenceRecordReader([s1, s2])
        it = SequenceRecordReaderDataSetIterator(reader, None, batch_size=2,
                                                 num_classes=2)
        ds = it.next()
        assert ds.features.shape == (2, 3, 2)
        assert ds.labels.shape == (2, 3, 2)
        np.testing.assert_array_equal(ds.features_mask, [[1, 1, 1], [1, 1, 0]])
        assert ds.labels[0, 1, 1] == 1.0  # t=1 label 1 one-hot

    def test_sequence_reader_bucketing(self, tmp_path):
        """bucket_boundaries pads T up to a fixed bucket (bounded XLA
        compile count) and hard-caps at the last boundary."""
        s1 = tmp_path / "s1.csv"
        s1.write_text("0.1,0.2,0\n0.3,0.4,1\n0.5,0.6,0\n")  # len 3
        s2 = tmp_path / "s2.csv"
        s2.write_text("0.7,0.8,1\n0.9,1.0,1\n")              # len 2
        reader = CSVSequenceRecordReader([s1, s2])
        it = SequenceRecordReaderDataSetIterator(
            reader, None, batch_size=2, num_classes=2,
            bucket_boundaries=[4, 8])
        ds = it.next()
        assert ds.features.shape == (2, 4, 2)     # bucketed up to 4
        np.testing.assert_array_equal(ds.features_mask,
                                      [[1, 1, 1, 0], [1, 1, 0, 0]])

        # hard cap: sequences longer than the last boundary truncate,
        # keeping the TAIL (ALIGN_END: final steps carry the targets)
        reader.reset()
        it2 = SequenceRecordReaderDataSetIterator(
            reader, None, batch_size=2, num_classes=2,
            bucket_boundaries=[2])
        ds2 = it2.next()
        assert ds2.features.shape == (2, 2, 2)
        np.testing.assert_array_equal(ds2.features_mask, [[1, 1], [1, 1]])
        # seq 1 (len 3) kept its LAST two steps: features 0.3..0.6
        np.testing.assert_allclose(ds2.features[0],
                                   [[0.3, 0.4], [0.5, 0.6]], rtol=1e-6)

        # non-positive boundaries are rejected at construction
        import pytest
        with pytest.raises(ValueError, match="positive"):
            SequenceRecordReaderDataSetIterator(
                reader, None, batch_size=2, num_classes=2,
                bucket_boundaries=[0])


class TestFetchers:
    def test_emnist_letters(self):
        it = EmnistDataSetIterator("letters", 16, num_examples=64)
        ds = next(iter(it))
        assert ds.features.shape == (16, 784)
        assert ds.labels.shape == (16, 26)

    def test_cifar10_nhwc(self):
        it = Cifar10DataSetIterator(8, num_examples=32)
        ds = next(iter(it))
        assert ds.features.shape == (8, 32, 32, 3)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    def test_unknown_emnist_split_raises(self):
        with pytest.raises(ValueError):
            EmnistDataSetIterator("nope", 8)


class TestMemoryReport:
    def test_lenet_style_report(self):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
                .layer(DenseLayer(n_out=100, activation="relu"))
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.convolutional(28, 28, 1)).build())
        report = memory_report(conf)
        assert len(report.layer_reports) == 3
        # conv params: 5*5*1*20 + 20 = 520 floats
        assert report.layer_reports[0].parameter_bytes == 520 * 4
        # Adam keeps 2 param-sized slots
        assert report.layer_reports[0].updater_state_bytes == 2 * 520 * 4
        assert report.total_bytes(32) > report.total_fixed_bytes()
        assert "TOTAL" in report.summary(32)

    def test_sgd_has_no_updater_state(self):
        from deeplearning4j_tpu.common.updaters import Sgd
        conf = (NeuralNetConfiguration.builder().updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        report = memory_report(conf)
        assert all(r.updater_state_bytes == 0 for r in report.layer_reports)


class TestModelGuesser:
    def test_guesses_checkpoint_and_keras(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        ckpt = tmp_path / "model.zip"
        ModelSerializer.write_model(net, ckpt)
        loaded = ModelGuesser.load_model_guess(ckpt)
        assert isinstance(loaded, MultiLayerNetwork)

        # Keras h5 path
        import json
        from deeplearning4j_tpu.modelimport import Hdf5Archive
        h5p = tmp_path / "m.h5"
        config = {"class_name": "Sequential", "config": [
            {"class_name": "Dense", "config": {
                "name": "d", "units": 3, "activation": "relu",
                "use_bias": True, "batch_input_shape": [None, 4]}}]}
        with Hdf5Archive(h5p, "w") as h5:
            h5.write_attr_string("model_config", json.dumps(config))
        guessed = ModelGuesser.load_model_guess(h5p)
        assert isinstance(guessed, MultiLayerNetwork)

        bad = tmp_path / "junk.bin"
        bad.write_bytes(b"garbage")
        with pytest.raises(ValueError):
            ModelGuesser.load_model_guess(bad)

    def _mln_conf(self):
        return (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())

    def test_config_guess_mln_json(self, tmp_path):
        p = tmp_path / "conf.json"
        p.write_text(self._mln_conf().to_json())
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        conf = ModelGuesser.load_config_guess(p)
        assert isinstance(conf, MultiLayerConfiguration)
        assert len(conf.layers) == 2

    def test_config_guess_graph_json(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        g = (ComputationGraphConfiguration.graph_builder(
                NeuralNetConfiguration.builder().updater(Adam(1e-3)))
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
             .add_layer("out", OutputLayer(n_in=8, n_out=2), "d")
             .set_outputs("out"))
        conf = g.build()
        p = tmp_path / "graph.json"
        p.write_text(conf.to_json())
        guessed = ModelGuesser.load_config_guess(p)
        assert isinstance(guessed, ComputationGraphConfiguration)
        # beyond-ref: model guess on a config file → initialized net
        net = ModelGuesser.load_model_guess(p)
        assert isinstance(net, ComputationGraph)
        assert net.params  # initialized

    def test_config_guess_from_checkpoint_zip(self, tmp_path):
        net = MultiLayerNetwork(self._mln_conf()).init()
        ckpt = tmp_path / "m.zip"
        ModelSerializer.write_model(net, ckpt)
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        conf = ModelGuesser.load_config_guess(ckpt)
        assert isinstance(conf, MultiLayerConfiguration)

    def test_config_guess_keras_architecture_json(self, tmp_path):
        import json
        arch = {"class_name": "Sequential", "config": [
            {"class_name": "Dense", "config": {
                "name": "d", "units": 3, "activation": "relu",
                "use_bias": True, "batch_input_shape": [None, 4]}}]}
        p = tmp_path / "arch.json"
        p.write_text(json.dumps(arch))
        conf = ModelGuesser.load_config_guess(p)
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        assert isinstance(conf, MultiLayerConfiguration)
        # model guess on the architecture file gives an initialized net
        net = ModelGuesser.load_model_guess(p)
        assert isinstance(net, MultiLayerNetwork)

    def test_model_guess_initializes_from_mln_config(self, tmp_path):
        p = tmp_path / "conf.json"
        p.write_text(self._mln_conf().to_json())
        net = ModelGuesser.load_model_guess(p)
        assert isinstance(net, MultiLayerNetwork)
        out = net.output(np.zeros((2, 4), np.float32))
        assert out.shape == (2, 2)


class TestNormalizers:
    def _batches(self, n=5, b=16, f=3, seed=0):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.default_rng(seed)
        return [DataSet(rng.normal(2.0, 3.0, (b, f)).astype(np.float32)
                        * np.array([1.0, 10.0, 0.1], np.float32))
                for _ in range(n)]

    def test_standardize_streaming_matches_full_batch(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        batches = self._batches()
        full = np.concatenate([b.features for b in batches])
        norm = NormalizerStandardize().fit(batches)
        np.testing.assert_allclose(norm.mean, full.mean(0), rtol=1e-6)
        np.testing.assert_allclose(norm.std, full.std(0), rtol=1e-5)
        z = norm.transform(full)
        np.testing.assert_allclose(z.mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(z.std(0), 1.0, atol=1e-4)
        back = norm.revert(z)
        np.testing.assert_allclose(back, full, atol=1e-4)

    def test_standardize_rank4_reduces_to_channels(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(4, 8, 8, 3)).astype(np.float32)
        norm = NormalizerStandardize().fit(DataSet(x))
        assert norm.mean.shape == (3,)
        np.testing.assert_allclose(norm.mean, x.mean((0, 1, 2)), rtol=1e-5)

    def test_minmax_scaler(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerMinMaxScaler)
        batches = self._batches()
        full = np.concatenate([b.features for b in batches])
        norm = NormalizerMinMaxScaler(-1.0, 1.0).fit(batches)
        z = norm.transform(full)
        np.testing.assert_allclose(z.min(0), -1.0, atol=1e-5)
        np.testing.assert_allclose(z.max(0), 1.0, atol=1e-5)
        np.testing.assert_allclose(norm.revert(z), full, atol=1e-3)

    def test_standardize_honors_features_mask_on_padded_corpus(self):
        """Padded timesteps must not enter the statistics — matching
        ND4J's masked-aware NormalizerStandardize: stats fit on a
        padded corpus (with features_mask) equal stats fit on the
        unpadded sequences (ADVICE r5)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        rng = np.random.default_rng(7)
        B, T, F = 4, 10, 3
        batches, real_rows = [], []
        for _ in range(3):
            x = np.zeros((B, T, F), np.float32)
            mask = np.zeros((B, T), np.float32)
            for i in range(B):
                L = int(rng.integers(3, T + 1))
                seq = rng.normal(5.0, 2.0, (L, F)).astype(np.float32)
                x[i, :L] = seq
                mask[i, :L] = 1.0
                real_rows.append(seq)
                # poison the padding: masked stats must not see it
                x[i, L:] = 1e6
            batches.append(DataSet(x, features_mask=mask))
        real = np.concatenate(real_rows)
        norm = NormalizerStandardize().fit(batches)
        np.testing.assert_allclose(norm.mean, real.mean(0), rtol=1e-6)
        np.testing.assert_allclose(norm.std, real.std(0), rtol=1e-5)

    def test_minmax_honors_features_mask_on_padded_corpus(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerMinMaxScaler)
        rng = np.random.default_rng(8)
        x = rng.uniform(-1.0, 1.0, (3, 6, 2)).astype(np.float32)
        mask = np.ones((3, 6), np.float32)
        mask[:, 4:] = 0.0
        x[:, 4:] = 99.0   # padding outside the real range
        norm = NormalizerMinMaxScaler().fit(DataSet(x, features_mask=mask))
        np.testing.assert_allclose(norm.data_max, x[:, :4].reshape(-1, 2).max(0))
        np.testing.assert_allclose(norm.data_min, x[:, :4].reshape(-1, 2).min(0))

    def test_fully_masked_corpus_fails_loudly(self):
        """An all-zero mask (upstream filtering bug) must raise at
        fit(), not produce NaN stats that poison every transform."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerMinMaxScaler,
            NormalizerStandardize,
        )
        x = np.ones((2, 4, 3), np.float32)
        mask = np.zeros((2, 4), np.float32)
        for cls in (NormalizerStandardize, NormalizerMinMaxScaler):
            with pytest.raises(ValueError, match="unmasked"):
                cls().fit(DataSet(x, features_mask=mask))

    def test_unmasked_fit_unchanged(self):
        """No mask → identical statistics to the seed behavior."""
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        batches = self._batches()
        full = np.concatenate([b.features for b in batches])
        norm = NormalizerStandardize().fit(batches)
        np.testing.assert_allclose(norm.mean, full.mean(0), rtol=1e-6)

    def test_image_scaler_stateless(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler)
        norm = ImagePreProcessingScaler(0.0, 1.0)
        x = np.array([[0, 127.5, 255]], np.float32)
        np.testing.assert_allclose(norm.transform(x), [[0, 0.5, 1.0]])
        np.testing.assert_allclose(norm.revert(norm.transform(x)), x)

    def test_pre_process_hook_mutates_dataset(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        batches = self._batches(n=2)
        norm = NormalizerStandardize().fit(batches)
        ds = batches[0]
        norm.pre_process(ds)
        assert abs(float(ds.features.mean())) < 1.0

    def test_normalizer_travels_inside_model_zip(self, tmp_path):
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        ckpt = tmp_path / "m.zip"
        ModelSerializer.write_model(net, ckpt)
        norm = NormalizerStandardize().fit(self._batches())
        ModelSerializer.add_normalizer_to_model(ckpt, norm)
        # double-add is an error (reference replaces via re-save)
        with pytest.raises(ValueError, match="already contains"):
            ModelSerializer.add_normalizer_to_model(ckpt, norm)
        # model still restores; normalizer restores beside it
        restored_net = ModelSerializer.restore_model(ckpt)
        assert isinstance(restored_net, MultiLayerNetwork)
        restored = ModelGuesser.load_normalizer(ckpt)
        np.testing.assert_allclose(restored.mean, norm.mean)
        np.testing.assert_allclose(restored.std, norm.std)
        # zip without a normalizer → None
        bare = tmp_path / "bare.zip"
        ModelSerializer.write_model(net, bare)
        assert ModelGuesser.load_normalizer(bare) is None

    def test_minmax_and_image_persist_round_trip(self, tmp_path):
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler, NormalizerMinMaxScaler,
            normalizer_from_meta)
        norm = NormalizerMinMaxScaler(0.0, 2.0).fit(self._batches())
        meta, arrays = norm.state()
        clone = normalizer_from_meta(meta, arrays)
        x = self._batches(n=1)[0].features
        np.testing.assert_allclose(clone.transform(x), norm.transform(x))
        img = ImagePreProcessingScaler(-1.0, 1.0, bits=16)
        meta, arrays = img.state()
        clone = normalizer_from_meta(meta, arrays)
        assert clone.bits == 16 and clone.a == -1.0


class TestNewListeners:
    def test_sleepy_and_param_listeners(self):
        lines = []
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init().set_listeners(
            SleepyTrainingListener(timer_iteration_ms=1),
            ParamAndGradientIterationListener(printer=lines.append))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        net.fit(x, y, epochs=2, batch_size=8)
        assert len(lines) == 2
        assert "0_W" in lines[0]


class TestEvalTools:
    def test_roc_html_export(self, tmp_path):
        from deeplearning4j_tpu.eval import ROC
        from deeplearning4j_tpu.eval.tools import EvaluationTools
        rng = np.random.default_rng(0)
        labels = rng.integers(0, 2, 200)
        probs = np.clip(labels * 0.6 + rng.random(200) * 0.5, 0, 1)
        roc = ROC()
        roc.eval(np.eye(2)[labels], np.stack([1 - probs, probs], 1))
        html = EvaluationTools.roc_chart_html(roc)
        assert "AUC" in html and "<svg" in html
        out = tmp_path / "roc.html"
        EvaluationTools.export_roc_charts_to_html_file(roc, out)
        assert out.read_text().startswith("<!doctype html>")

    def test_calibration_html(self):
        from deeplearning4j_tpu.eval import EvaluationCalibration
        from deeplearning4j_tpu.eval.tools import EvaluationTools
        rng = np.random.default_rng(1)
        labels = np.eye(2)[rng.integers(0, 2, 100)]
        preds = rng.dirichlet((1, 1), 100)
        cal = EvaluationCalibration()
        cal.eval(labels, preds)
        html = EvaluationTools.calibration_chart_html(cal, 2)
        assert "reliability" in html


class TestGraphGradientCheck:
    def test_small_graph_passes(self):
        from deeplearning4j_tpu.gradientcheck import check_graph_gradients
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        b = NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
        g = ComputationGraphConfiguration.graph_builder(b)
        g.add_inputs("in")
        g.set_input_types(InputType.feed_forward(5))
        g.add_layer("a", DenseLayer(n_out=7, activation="tanh"), "in")
        g.add_layer("b", DenseLayer(n_out=7, activation="sigmoid"), "in")
        g.add_vertex("m", MergeVertex(), "a", "b")
        g.add_layer("out", OutputLayer(n_out=3), "m")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 5))
        y = np.eye(3)[rng.integers(0, 3, 4)]
        ok, worst, fails = check_graph_gradients(net, x, y)
        assert ok, f"worst {worst}: {fails[:3]}"


class TestNativeCsv:
    """Native C++ CSV parser (native/csv/dl4j_csv.cpp) with NumPy
    fallback — DataVec CSVRecordReader bulk-numeric role."""

    def _write(self, tmp_path, text, name="data.csv"):
        p = tmp_path / name
        p.write_text(text)
        return str(p)

    def test_matrix_parse_matches_numpy(self, tmp_path):
        from deeplearning4j_tpu.datasets.native_csv import (
            load_csv_matrix, native_available)
        rng = np.random.default_rng(0)
        m = rng.standard_normal((50, 7)).astype(np.float32)
        path = self._write(tmp_path, "\n".join(
            ",".join(f"{v:.6g}" for v in row) for row in m))
        got = load_csv_matrix(path)
        assert got.shape == (50, 7)
        np.testing.assert_allclose(got, m, rtol=1e-5)
        assert native_available()  # g++ is baked into this image

    def test_header_comments_crlf_and_nan(self, tmp_path):
        from deeplearning4j_tpu.datasets.native_csv import load_csv_matrix
        path = self._write(
            tmp_path,
            "a,b,c\r\n# comment line\r\n1,2,3\r\n4,oops,6\r\n")
        got = load_csv_matrix(path, skip_header=1)
        assert got.shape == (2, 3)
        assert got[0].tolist() == [1.0, 2.0, 3.0]
        assert np.isnan(got[1, 1]) and got[1, 2] == 6.0

    def test_csv_dataset_classification(self, tmp_path):
        from deeplearning4j_tpu.datasets.native_csv import load_csv_dataset
        path = self._write(tmp_path, "1.0,2.0,0\n3.0,4.0,2\n5.0,6.0,1\n")
        ds = load_csv_dataset(path, label_index=-1)
        assert ds.features.shape == (3, 2)
        assert ds.labels.shape == (3, 3)
        assert ds.labels.argmax(axis=1).tolist() == [0, 2, 1]

    def test_csv_dataset_regression_and_delimiter(self, tmp_path):
        from deeplearning4j_tpu.datasets.native_csv import load_csv_dataset
        path = self._write(tmp_path, "1.0;2.0;0.5\n3.0;4.0;1.5\n")
        ds = load_csv_dataset(path, label_index=-1, regression=True,
                              delimiter=";")
        assert ds.labels.ravel().tolist() == [0.5, 1.5]

    def test_bad_class_labels_raise(self, tmp_path):
        import pytest
        from deeplearning4j_tpu.datasets.native_csv import load_csv_dataset
        p = tmp_path / "bad.csv"
        p.write_text("1.0,2.0,cat\n3.0,4.0,1\n")
        with pytest.raises(ValueError, match="non-numeric"):
            load_csv_dataset(str(p))
        p2 = tmp_path / "frac.csv"
        p2.write_text("1.0,2.0,0.5\n")
        with pytest.raises(ValueError, match="integers"):
            load_csv_dataset(str(p2))

    def test_quoted_fields_keep_column_alignment(self, tmp_path):
        # regression: a quoted field with an embedded delimiter must not
        # shift subsequent columns
        from deeplearning4j_tpu.datasets.native_csv import (
            load_csv_matrix, native_available)
        assert native_available()
        p = tmp_path / "q.csv"
        p.write_text('"1,234",5\n7,8\n')
        got = load_csv_matrix(str(p))
        assert got.shape == (2, 2)
        assert got[0, 1] == 5.0 and got[1].tolist() == [7.0, 8.0]

    def test_fallback_matches_native_comment_semantics(self, tmp_path):
        from deeplearning4j_tpu.datasets import native_csv
        p = tmp_path / "c.csv"
        p.write_text("# generated\ncolA,colB\n1,2\n3,4\n")
        native = native_csv.load_csv_matrix(str(p), skip_header=1)
        fallback = native_csv._numpy_fallback(str(p), ",", 1)
        np.testing.assert_array_equal(native, fallback)
        assert native.shape == (2, 2)

    def test_fallback_quote_aware_and_ragged_padding(self, tmp_path):
        from deeplearning4j_tpu.datasets import native_csv
        p = tmp_path / "fq.csv"
        p.write_text('"1,234",5\n7\n8,9\n')
        got = native_csv._numpy_fallback(str(p), ",", 0)
        assert got.shape == (3, 2)
        assert got[0, 1] == 5.0
        assert np.isnan(got[1, 1]) and got[1, 0] == 7.0
