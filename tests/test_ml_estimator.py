"""sklearn-style Estimator/Transformer adapters (reference
`dl4j-spark-ml SparkDl4jNetwork.scala` / `AutoEncoder.scala`)."""

import numpy as np
import jax
from jax.sharding import Mesh

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.ml import AutoEncoderEstimator, NetworkEstimator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import SharedTrainingMaster


def iris_conf():
    return (NeuralNetConfiguration.builder().seed(42).updater(Adam(0.02))
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())


class TestNetworkEstimator:
    def test_fit_predict_score(self):
        x, y = load_iris()
        est = NetworkEstimator(iris_conf, epochs=30, batch_size=50)
        est.fit(x, y.argmax(axis=1))
        assert est.score(x, y.argmax(axis=1)) > 0.9
        proba = est.predict_proba(x[:5])
        assert proba.shape == (5, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)

    def test_accepts_one_hot_labels_and_transform(self):
        x, y = load_iris()
        est = NetworkEstimator(iris_conf, epochs=5)
        est.fit(x, y)           # already one-hot
        assert est.transform(x).shape == (150, 3)

    def test_params_roundtrip_sklearn_contract(self):
        est = NetworkEstimator(iris_conf, epochs=3)
        params = est.get_params()
        assert params["epochs"] == 3
        est.set_params(epochs=7, batch_size=16)
        assert est.epochs == 7 and est.batch_size == 16

    def test_distributed_fit_via_training_master(self):
        x, y = load_iris()
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        master = SharedTrainingMaster(batch_size_per_worker=25, mesh=mesh,
                                      collect_training_stats=False)
        est = NetworkEstimator(iris_conf, epochs=20, training_master=master)
        est.fit(x, y.argmax(axis=1))
        assert est.score(x, y.argmax(axis=1)) > 0.85

    def test_works_in_sklearn_pipeline_if_available(self):
        try:
            from sklearn.pipeline import Pipeline
            from sklearn.preprocessing import StandardScaler
        except ImportError:
            return
        x, y = load_iris()
        pipe = Pipeline([("scale", StandardScaler()),
                         ("net", NetworkEstimator(iris_conf, epochs=20))])
        pipe.fit(x, y.argmax(axis=1))
        assert pipe.score(x, y.argmax(axis=1)) > 0.9


class TestAutoEncoderEstimator:
    def test_codes_and_reconstruction(self):
        rng = np.random.default_rng(0)
        # two well-separated blobs in 8-d
        a = rng.normal(0.2, 0.05, (40, 8))
        b = rng.normal(0.8, 0.05, (40, 8))
        X = np.vstack([a, b]).astype(np.float32)
        est = AutoEncoderEstimator(n_hidden=3, epochs=60, batch_size=20,
                                   learning_rate=5e-2, corruption_level=0.0)
        codes = est.fit_transform(X)
        assert codes.shape == (80, 3)
        est.output = "reconstruction"
        recon = est.transform(X)
        assert recon.shape == X.shape
        # reconstruction error must beat predicting the global mean
        mse = float(((recon - X) ** 2).mean())
        base = float(((X.mean(axis=0) - X) ** 2).mean())
        assert mse < base
