"""MoE (expert parallelism) + pipeline parallelism tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, MixtureOfExperts, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    MeshSpec,
    ShardedParallelTrainer,
    make_mesh,
    moe_param_specs,
    pipeline_forward,
)

requires_8dev = pytest.mark.skipif(len(jax.devices()) < 8,
                                   reason="needs 8 virtual devices")


class TestMoE:
    def _conf(self, top_k=2):
        return (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(MixtureOfExperts(n_experts=4, hidden_size=16,
                                        top_k=top_k))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())

    def test_param_shapes(self):
        net = MultiLayerNetwork(self._conf()).init()
        p = net.params["0"]
        assert p["Wg"].shape == (8, 4)
        assert p["We1"].shape == (4, 8, 16)
        assert p["We2"].shape == (4, 16, 8)

    def test_gates_renormalised_topk(self):
        layer = MixtureOfExperts(n_in=8, n_out=8, n_experts=4, hidden_size=8,
                                 top_k=2)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (5, 8))
        gates, aux = layer._gate(params, x)
        g = np.asarray(gates)
        assert ((g > 0).sum(axis=-1) <= 2).all()
        np.testing.assert_allclose(g.sum(axis=-1), 1.0, rtol=1e-5)
        assert np.isfinite(float(aux))

    def test_training_decreases_loss(self):
        net = MultiLayerNetwork(self._conf()).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        s0 = float(net.score(DataSet(x, y)))
        net.fit(x, y, epochs=20, batch_size=64)
        assert float(net.score(DataSet(x, y))) < s0

    @requires_8dev
    def test_expert_parallel_training(self):
        net = MultiLayerNetwork(self._conf()).init()
        mesh = make_mesh(MeshSpec.of(data=2, expert=4))
        specs = moe_param_specs(net, "expert")
        assert specs["0"]["We1"] == P("expert", None, None)
        assert specs["0"]["Wg"] == P()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        s0 = float(net.score(DataSet(x, y)))
        ShardedParallelTrainer(net, mesh, param_specs=specs).fit(
            x, y, epochs=5, batch_size=64)
        assert float(net.score(DataSet(x, y))) < s0


class TestPipeline:
    def _block(self, params, x):
        return jnp.tanh(x @ params["W"] + params["b"])

    def _stacked_params(self, S, F, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "W": jnp.asarray(rng.standard_normal((S, F, F)) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((S, F)) * 0.1, jnp.float32),
        }

    def _sequential(self, params, x, S):
        for s in range(S):
            x = self._block(jax.tree_util.tree_map(lambda a: a[s], params), x)
        return x

    @requires_8dev
    @pytest.mark.parametrize("S", [2, 4, 8])
    def test_matches_sequential(self, S):
        F = 6
        params = self._stacked_params(S, F)
        mesh = make_mesh(MeshSpec.of(pipe=S))
        x = jnp.asarray(np.random.default_rng(1).standard_normal((8, F)),
                        jnp.float32)
        got = pipeline_forward(self._block, params, x, mesh, microbatches=4)
        want = self._sequential(params, x, S)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    @requires_8dev
    @pytest.mark.slow   # 48s; schedule parity (test_matches_sequential) and the
    # container-level PP parity tests keep pipeline-grad coverage in the default run
    def test_differentiable_and_trains(self):
        S, F = 4, 6
        params = self._stacked_params(S, F)
        mesh = make_mesh(MeshSpec.of(pipe=S))
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((8, F)), jnp.float32)
        target = jnp.asarray(rng.standard_normal((8, F)), jnp.float32)

        def loss(p):
            out = pipeline_forward(self._block, p, x, mesh, microbatches=4)
            return jnp.mean((out - target) ** 2)

        # gradient parity with the sequential computation
        def loss_seq(p):
            return jnp.mean((self._sequential(p, x, S) - target) ** 2)

        g_pipe = jax.grad(loss)(params)
        g_seq = jax.grad(loss_seq)(params)
        for k in params:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-4, atol=1e-6)
        # a few SGD steps reduce the loss
        l0 = float(loss(params))
        for _ in range(10):
            g = jax.grad(loss)(params)
            params = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                            params, g)
        assert float(loss(params)) < l0


class TestMoEFusedDispatch:
    """Layers that emit train-only state (MoE aux_loss, popped by the
    loss) must not break the fused `steps_per_execution` scan: the scan
    carry keeps the init-time state structure."""

    def _net(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.common.weights import WeightInit
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import MixtureOfExperts, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Adam(1e-2)).weight_init(WeightInit.XAVIER)
                .list()
                .layer(MixtureOfExperts(n_experts=4, hidden_size=16, top_k=2))
                .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_container_fused_steps(self):
        import numpy as np
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        net = self._net()
        net.fit(x, y, epochs=2, batch_size=16, shuffle=False,
                steps_per_execution=4)
        assert net.iteration_count == 8
        for v in net.param_table().values():
            assert np.all(np.isfinite(np.asarray(v)))

    def test_parallel_trainer_fused_steps(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
        from deeplearning4j_tpu.parallel import ParallelTrainer
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        net = self._net()
        ParallelTrainer(net, device_mesh(), mode="sync").fit(
            ArrayDataSetIterator(x, y, batch_size=32, shuffle=False),
            epochs=2, steps_per_execution=2)
        assert net.iteration_count == 4
        for v in net.param_table().values():
            assert np.all(np.isfinite(np.asarray(v)))
