"""Parallel data iterators + device prefetch (reference
`datasets/iterator/parallel/JointParallelDataSetIterator.java`,
`FileSplitParallelDataSetIterator.java`)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    ArrayDataSetIterator,
    DataSet,
    DevicePrefetchIterator,
    FileSplitParallelDataSetIterator,
    InequalityHandling,
    JointParallelDataSetIterator,
)


def _iter(n_batches, tag, batch=4):
    """n_batches batches whose features are constant `tag`."""
    x = np.full((n_batches * batch, 3), tag, np.float32)
    y = np.zeros((n_batches * batch, 2), np.float32)
    return ArrayDataSetIterator(x, y, batch_size=batch, shuffle=False)


class TestJointParallel:
    def test_round_robin_interleaves(self):
        it = JointParallelDataSetIterator(
            [_iter(2, 1.0), _iter(2, 2.0)], prefetch=0)
        tags = [float(ds.features[0, 0]) for ds in it]
        assert tags == [1.0, 2.0, 1.0, 2.0]

    def test_stop_everyone(self):
        it = JointParallelDataSetIterator(
            [_iter(1, 1.0), _iter(3, 2.0)],
            inequality_handling=InequalityHandling.STOP_EVERYONE, prefetch=0)
        tags = [float(ds.features[0, 0]) for ds in it]
        # producer 0 depletes when asked for its 2nd batch → stop
        assert tags == [1.0, 2.0]

    def test_relocate_drains_longer_producers(self):
        it = JointParallelDataSetIterator(
            [_iter(1, 1.0), _iter(3, 2.0)],
            inequality_handling=InequalityHandling.RELOCATE, prefetch=0)
        tags = [float(ds.features[0, 0]) for ds in it]
        assert tags == [1.0, 2.0, 2.0, 2.0]

    def test_pass_null_yields_none(self):
        it = JointParallelDataSetIterator(
            [_iter(1, 1.0), _iter(2, 2.0)],
            inequality_handling=InequalityHandling.PASS_NULL, prefetch=0)
        tags = [None if ds is None else float(ds.features[0, 0]) for ds in it]
        # depleted producer 0 yields None on each of its turns until the
        # last producer also depletes
        assert tags == [1.0, 2.0, None, 2.0, None]

    def test_reset_wraps_until_all_depleted(self):
        it = JointParallelDataSetIterator(
            [_iter(1, 1.0), _iter(2, 2.0)],
            inequality_handling=InequalityHandling.RESET, prefetch=0)
        tags = [float(ds.features[0, 0]) for ds in it]
        # producer 0 resets once; iteration ends when both have wrapped
        assert tags[:4] == [1.0, 2.0, 1.0, 2.0]
        assert len(tags) >= 4

    def test_async_buffered_mode(self):
        it = JointParallelDataSetIterator(
            [_iter(3, 1.0), _iter(3, 2.0)], prefetch=2)
        tags = [float(ds.features[0, 0]) for ds in it]
        assert tags == [1.0, 2.0] * 3


class TestFileSplitParallel:
    def _tree(self, tmp_path, n=6):
        for i in range(n):
            np.save(tmp_path / f"part{i}.npy",
                    np.full((4, 3), float(i), np.float32))
        (tmp_path / "ignore.txt").write_text("not a batch")
        return tmp_path

    def test_pattern_split_and_callback(self, tmp_path):
        self._tree(tmp_path)

        def cb(path):
            x = np.load(path)
            return DataSet(x, np.zeros((len(x), 2), np.float32))

        it = FileSplitParallelDataSetIterator(
            str(tmp_path), "*.npy", cb, num_producers=2, prefetch=0)
        tags = sorted(float(ds.features[0, 0]) for ds in it)
        assert tags == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        assert len(it.paths) == 6

    def test_no_match_raises(self, tmp_path):
        with pytest.raises(ValueError):
            FileSplitParallelDataSetIterator(str(tmp_path), "*.npy",
                                             lambda p: None)


class TestDevicePrefetch:
    def test_batches_land_on_device_and_train(self):
        import jax

        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        base = ArrayDataSetIterator(x, y, batch_size=16, shuffle=False)
        it = DevicePrefetchIterator(base, depth=2)
        seen = list(it)
        assert len(seen) == 4
        assert all(isinstance(ds.features, jax.Array) for ds in seen)

        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        it.reset()
        net.fit(it, epochs=3)
        assert net.score_value < 1.2


class TestAsyncEarlyAbandon:
    def test_break_consumer_reaps_worker_thread(self):
        """A consumer that abandons AsyncDataSetIterator mid-epoch must
        not leave the prefetch worker blocked forever on the bounded
        queue put (daemon-thread leak): closing the generator signals
        the worker to stop, drains the queue, and joins the thread."""
        import gc
        import threading
        import time

        from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator

        base = _iter(50, 1.0)           # far more batches than consumed
        before = set(threading.enumerate())
        a = AsyncDataSetIterator(base, prefetch=1)
        for ds in a:                     # prefetch=1: queue fills, the
            break                        # worker blocks in q.put — abandon
        gc.collect()                     # close the abandoned generator
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            leaked = [t for t in set(threading.enumerate()) - before
                      if t.is_alive()]
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked, f"prefetch worker leaked: {leaked}"
        # the iterator is still usable afterwards (fresh worker per epoch)
        assert sum(1 for _ in a) == 50

    def test_exhausted_consumer_unchanged(self):
        from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator
        a = AsyncDataSetIterator(_iter(5, 2.0), prefetch=2)
        seen = [ds for ds in a]
        assert len(seen) == 5
        assert float(seen[0].features[0, 0]) == 2.0


def test_reset_mode_tolerates_empty_producer():
    # a zero-batch producer must be dropped, not busy-looped (regression)
    empty = ArrayDataSetIterator(np.zeros((0, 3), np.float32),
                                 np.zeros((0, 2), np.float32), batch_size=4)
    it = JointParallelDataSetIterator(
        [empty, _iter(2, 2.0)],
        inequality_handling=InequalityHandling.RESET, prefetch=0)
    tags = [float(ds.features[0, 0]) for ds in it]
    assert 2.0 in tags and len(tags) >= 2


def test_parallel_trainer_rejects_all_indivisible_batches():
    import jax
    import pytest
    from jax.sharding import Mesh

    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((30, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 30)]
    tr = ParallelTrainer(net, mesh, mode="sync")
    with pytest.raises(ValueError, match="indivisible"):
        tr.fit(x, y, epochs=1, batch_size=10)   # 10 % 4 != 0 for every batch
    # divisible batches with a ragged tail still train (tail dropped)
    tr2 = ParallelTrainer(net, mesh, mode="sync")
    tr2.fit(x, y, epochs=1, batch_size=8)       # 8,8,8 train; tail 6 dropped
