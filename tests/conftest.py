"""Test config: force the CPU backend with 8 virtual devices so mesh /
sharding tests run without TPU hardware (the Spark `local[N]` idea from
the reference test suite, SURVEY.md §4).

The axon TPU plugin registers itself at interpreter start (sitecustomize)
and forces `jax_platforms="axon,cpu"` via jax config — so env vars alone
are too late. We update the config explicitly before any backend
initialization.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache: the suite is compile-dominated on
# the 1-core sandbox (measured 3.5x on compile-heavy files), so warm
# reruns fit the driver's single 600 s window. Programs are keyed by
# HLO — code changes recompile exactly what they touch. The cache dir
# is fingerprinted by the host's CPU feature set: sandbox sessions
# migrate between machine types, and XLA:CPU AOT results compiled for
# another machine load with "may SIGILL" warnings.
from deeplearning4j_tpu.nd import enable_compilation_cache  # noqa: E402


def _machine_tag():
    import hashlib
    import platform
    try:
        with open("/proc/cpuinfo") as f:
            flags = next((line for line in f if line.startswith("flags")), "")
    except OSError:
        flags = ""
    # fingerprint the full toolchain, not just the CPU: entries AOT-
    # compiled by another jaxlib build load "successfully" and then
    # corrupt the heap mid-suite (observed: malloc_consolidate abort
    # from a cache dir written by a previous sandbox image) — a version
    # change must land in a fresh namespace
    import jaxlib
    versions = (jax.__version__ + getattr(jaxlib, "__version__", "")
                + platform.python_version())
    return hashlib.sha256(
        (platform.machine() + flags + versions).encode()).hexdigest()[:10]


if not os.environ.get("DL4J_DISABLE_XLA_CACHE"):
    enable_compilation_cache(
        os.environ.get("DL4J_TEST_XLA_CACHE",
                       os.path.expanduser(
                           f"~/.cache/dl4tpu-xla-tests-{_machine_tag()}")),
        min_compile_time_secs=0.2)


# ---------------------------------------------------- suite budget report
# Per-file duration accounting for the tier-1 gate: the suite runs in a
# single hard window (driver: 600 s; ROADMAP timeout -k 10 870), and at
# ~8% headroom a silent overflow loses the whole round's verification.
# These hooks ride INSIDE the verbatim ROADMAP command (they are repo
# conftest code, not extra flags) and leave a JSON report that
# scripts/verify.sh turns into a top-offenders table + a soft-budget
# warning above 480 s.
import collections as _collections
import json as _json

_FILE_DURATIONS = _collections.defaultdict(float)
_DURATIONS_OUT = os.environ.get("DL4J_SUITE_DURATIONS",
                                "/tmp/_t1_durations.json")
SUITE_BUDGET_SOFT_S = 480.0
SUITE_BUDGET_HARD_S = 600.0


def pytest_runtest_logreport(report):
    # setup + call + teardown all charged to the test's file
    _FILE_DURATIONS[report.location[0]] += getattr(report, "duration",
                                                   0.0) or 0.0


def pytest_sessionfinish(session, exitstatus):
    if not _FILE_DURATIONS:
        return
    total = sum(_FILE_DURATIONS.values())
    files = sorted(({"file": f, "seconds": round(s, 2)}
                    for f, s in _FILE_DURATIONS.items()),
                   key=lambda r: -r["seconds"])
    try:
        with open(_DURATIONS_OUT, "w") as f:
            _json.dump({"total_seconds": round(total, 2),
                        "budget_soft_seconds": SUITE_BUDGET_SOFT_S,
                        "budget_hard_seconds": SUITE_BUDGET_HARD_S,
                        "files": files}, f, indent=1)
            f.write("\n")
    except OSError:
        pass
