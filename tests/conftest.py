"""Test config: force the CPU backend with 8 virtual devices so mesh /
sharding tests run without TPU hardware (the Spark `local[N]` idea from
the reference test suite, SURVEY.md §4)."""

import os
import sys

# Must happen before jax import anywhere.
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable axon TPU plugin registration
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
