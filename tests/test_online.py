"""Online-learning runtime (deeplearning4j_tpu/online/): the
unbounded-iterator contract, watermarked windowed normalizer stats,
drift-gated publish listener, OnlineTrainer, and the
resume-from-offset bit-parity guarantee."""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.common.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator
from deeplearning4j_tpu.datasets.normalizers import (
    NormalizerStandardize,
    normalizer_from_meta,
)
from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.online import (
    DriftGate,
    OnlineTrainer,
    StreamingDataSetIterator,
    WindowedStandardize,
)
from deeplearning4j_tpu.optimize.listeners import CollectScoresListener
from deeplearning4j_tpu.serving import ModelRegistry
from deeplearning4j_tpu.streaming import (
    LocalLogTransport,
    LocalQueueTransport,
    serialize_ndarray,
)

F, C, B = 6, 3, 8


def tiny_net(seed=7, lr=0.1):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
    lb = b.list().layer(DenseLayer(n_in=F, n_out=8, activation="tanh"))
    return MultiLayerNetwork(
        lb.layer(OutputLayer(n_in=8, n_out=C, activation="softmax",
                             loss="mcxent"))
          .set_input_type(InputType.feed_forward(F)).build()).init()


_W_TRUE = np.random.default_rng(42).standard_normal((F, C))


def make_records(n, seed, shuffle_labels=False):
    """Record = [features F | one-hot label C] concatenated."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal(F).astype(np.float32)
        cls = (int(rng.integers(0, C)) if shuffle_labels
               else int(np.argmax(x @ _W_TRUE)))
        y = np.zeros(C, np.float32)
        y[cls] = 1.0
        out.append(np.concatenate([x, y]))
    return out


def split_record(r):
    return r[:F], r[F:]


def fill_log(records, topic="train", transport=None):
    t = transport or LocalLogTransport()
    for r in records:
        t.send(topic, serialize_ndarray(r))
    return t


def make_stream(transport, topic="train", batch_size=B, **kw):
    kw.setdefault("watermark_timeout_s", 0.4)
    kw.setdefault("poll_s", 0.02)
    return StreamingDataSetIterator(
        transport, topic, batch_size=batch_size,
        record_to_example=split_record, **kw)


def params_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


# ===================================================== LocalLogTransport
class TestLocalLogTransport:
    def test_offset_reads_are_stable(self):
        t = LocalLogTransport()
        for i in range(5):
            t.send("a", bytes([i]))
        assert t.read("a", 2) == bytes([2])
        assert t.read("a", 2) == bytes([2])     # retained, re-readable
        assert t.producer_offset("a") == 5

    def test_read_blocks_until_producer_reaches_offset(self):
        t = LocalLogTransport()

        def late_send():
            time.sleep(0.1)
            t.send("a", b"x")

        threading.Thread(target=late_send, daemon=True).start()
        assert t.read("a", 0, timeout=5.0) == b"x"
        with pytest.raises(TimeoutError):
            t.read("a", 7, timeout=0.05)

    def test_receive_is_queue_compatible(self):
        t = LocalLogTransport()
        t.send("a", b"0")
        t.send("a", b"1")
        assert t.receive("a") == b"0"
        assert t.receive("a") == b"1"
        with pytest.raises(TimeoutError):
            t.receive("a", timeout=0.05)
        # the log is retained: offset reads still see consumed messages
        assert t.read("a", 0) == b"0"

    def test_close_drops_topic(self):
        t = LocalLogTransport()
        t.send("a", b"0")
        t.close("a")
        assert t.producer_offset("a") == 0


# ================================================ StreamingDataSetIterator
class TestStreamingIterator:
    def test_fixed_shape_batches_with_ragged_holdback(self):
        t = fill_log(make_records(2 * B + 3, seed=0))
        it = make_stream(t)
        batches = list(it)
        # 3 tail records held back — never dispatched as a short batch
        assert len(batches) == 2
        assert batches[0].features.shape == (B, F)
        assert batches[0].labels.shape == (B, C)
        assert it.cursor()["batch"] == 2
        assert it.cursor()["offset"] == 2 * B

    def test_cursor_counts_before_yield(self):
        t = fill_log(make_records(2 * B, seed=1))
        it = make_stream(t)
        gen = iter(it)
        next(gen)
        # the consumer HOLDS batch 1 — the cursor must include it
        assert it.cursor()["batch"] == 1
        gen.close()

    def test_watermark_timeout_ends_pass_then_resumes(self):
        t = fill_log(make_records(B, seed=2))
        it = make_stream(t)
        assert len(list(it)) == 1       # quiesced after the watermark
        fill_log(make_records(B, seed=3), transport=t)
        assert len(list(it)) == 1       # a later pass picks up new data
        assert it.cursor()["batch"] == 2

    def test_stop_ends_stream_at_batch_boundary(self):
        t = fill_log(make_records(8 * B, seed=4))
        it = make_stream(t, watermark_timeout_s=5.0)
        got = []
        for ds in it:
            got.append(ds)
            if len(got) == 2:
                it.stop()
        assert len(got) == 2

    def test_seek_replays_identical_batches(self):
        t = fill_log(make_records(4 * B, seed=5))
        ref = list(make_stream(t))
        it = make_stream(t)
        it.seek({"batch": 2, "batch_size": B})
        replay = list(it)
        assert len(replay) == 2
        for a, b_ in zip(ref[2:], replay):
            np.testing.assert_array_equal(a.features, b_.features)
            np.testing.assert_array_equal(a.labels, b_.labels)

    def test_seek_batch_size_mismatch_raises(self):
        it = make_stream(LocalLogTransport())
        with pytest.raises(ValueError, match="batch_size"):
            it.seek({"batch": 1, "batch_size": B + 1})

    def test_seek_over_destructive_queue_skips_replayed_prefix(self):
        # replay-from-offset over a destructive transport = the
        # producer republishes from the start and the iterator skips
        # the consumed prefix
        records = make_records(3 * B, seed=6)
        t = LocalQueueTransport()
        ref = list(make_stream(fill_log(records)))
        for r in records:
            t.send("train", serialize_ndarray(r))
        it = make_stream(t)
        it.seek({"batch": 1, "batch_size": B})
        replay = list(it)
        assert len(replay) == 2
        np.testing.assert_array_equal(replay[0].features,
                                      ref[1].features)

    def test_shape_change_mid_stream_is_loud(self):
        t = LocalLogTransport()
        t.send("train", serialize_ndarray(
            np.zeros(F + C, np.float32)))
        t.send("train", serialize_ndarray(
            np.zeros(F + C + 1, np.float32)))
        it = make_stream(t)
        with pytest.raises(ValueError, match="fixed-shape"):
            list(it)

    def test_metrics_families(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            t = fill_log(make_records(2 * B, seed=7), topic="m1")
            it = make_stream(t, topic="m1")
            list(it)
            snap = reg.snapshot()
            rec = snap["streaming_records_consumed_total"]["values"]
            assert any(e["labels"].get("topic") == "m1"
                       and e["value"] == 2 * B for e in rec)
            lag = snap["streaming_lag_records"]["values"]
            assert any(e["labels"].get("topic") == "m1"
                       and e["value"] == 0 for e in lag)
            age = snap["streaming_watermark_age_seconds"]["values"]
            assert any(e["labels"].get("topic") == "m1"
                       and e["value"] >= 0 for e in age)
        finally:
            monitor.disable()


# ============================================= async over unbounded source
class TestAsyncOverUnbounded:
    def test_abandon_does_not_strand_prefetch_thread(self):
        """Satellite regression: a consumer breaking out while the
        prefetch worker is blocked in a WATERMARK wait (not the
        bounded put) must unblock it promptly via the abandon hook."""
        t = fill_log(make_records(3 * B, seed=8))
        base = make_stream(t, watermark_timeout_s=60.0)   # would hang
        ait = AsyncDataSetIterator(base, prefetch=2)
        before = threading.active_count()
        t0 = time.monotonic()
        for i, _ in enumerate(ait):
            if i == 1:
                break                        # early abandon
        assert time.monotonic() - t0 < 5.0
        time.sleep(0.2)
        assert threading.active_count() <= before

    def test_cursor_counts_consumed_not_prefetched(self):
        t = fill_log(make_records(5 * B, seed=9))
        base = make_stream(t, watermark_timeout_s=60.0)
        ait = AsyncDataSetIterator(base, prefetch=4)
        gen = iter(ait)
        for _ in range(2):
            next(gen)
        # the worker ran ahead; the checkpointable position is what the
        # CONSUMER took — prefetched batches must replay after restore
        deadline = time.monotonic() + 5.0
        while (base.cursor()["batch"] <= 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert base.cursor()["batch"] > 2
        assert ait.cursor()["batch"] == 2
        gen.close()

    def test_seek_through_async_wrapper(self):
        t = fill_log(make_records(4 * B, seed=10))
        ref = list(make_stream(t))
        base = make_stream(t)
        ait = AsyncDataSetIterator(base, prefetch=2)
        ait.seek({"batch": 3, "batch_size": B})
        got = list(ait)
        assert len(got) == 1
        np.testing.assert_array_equal(got[0].features, ref[3].features)
        assert ait.cursor()["batch"] == 4


# ==================================================== WindowedStandardize
class TestWindowedStandardize:
    def test_window_matches_direct_stats_over_last_batches(self):
        rng = np.random.default_rng(0)
        w = WindowedStandardize(window=3)
        batches = [rng.standard_normal((B, F)) + i for i in range(6)]
        for x in batches:
            w.observe(x)
        tail = np.concatenate(batches[-3:])
        np.testing.assert_allclose(w.mean, tail.mean(axis=0),
                                   rtol=1e-10)
        np.testing.assert_allclose(w.std, tail.std(axis=0),
                                   rtol=1e-6)

    def test_transform_before_data_is_loud(self):
        with pytest.raises(ValueError, match="no data"):
            WindowedStandardize().transform(np.zeros((2, F)))

    def test_snapshot_is_frozen_and_versioned(self):
        rng = np.random.default_rng(1)
        w = WindowedStandardize(window=2)
        w.observe(rng.standard_normal((B, F)))
        s1 = w.snapshot()
        mean1 = np.array(s1.mean)
        w.observe(rng.standard_normal((B, F)) + 10.0)
        s2 = w.snapshot()
        np.testing.assert_array_equal(s1.mean, mean1)   # frozen
        assert (s1.version, s2.version) == (1, 2)
        assert s2.records_seen == 2 * B
        assert not np.allclose(s1.mean, s2.mean)

    def test_live_window_state_round_trip(self):
        rng = np.random.default_rng(2)
        w = WindowedStandardize(window=4)
        for i in range(6):
            w.observe(rng.standard_normal((B, F)) * (i + 1))
        w.snapshot()
        meta, arrays = w.state()
        w2 = normalizer_from_meta(meta, arrays)
        np.testing.assert_array_equal(w2.mean, w.mean)
        np.testing.assert_array_equal(w2.std, w.std)
        assert w2.snapshot_version == w.snapshot_version
        assert w2.records_seen == w.records_seen
        # the restored window EVICTS identically as new data arrives
        x = rng.standard_normal((B, F))
        w.observe(x)
        w2.observe(x)
        np.testing.assert_array_equal(w2.mean, w.mean)

    def test_snapshot_rides_the_published_zip(self, tmp_path):
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        rng = np.random.default_rng(3)
        w = WindowedStandardize(window=2)
        w.observe(rng.standard_normal((B, F)))
        reg = ModelRegistry(tmp_path)
        v = reg.publish("m", tiny_net(), normalizer=w.snapshot())
        restored = ModelSerializer.restore_normalizer_from_file(
            reg.path("m", v))
        np.testing.assert_array_equal(restored.mean, w.mean)
        assert restored.version == 1
        assert restored.records_seen == B
        # and transforms like a plain standardizer
        assert isinstance(restored, NormalizerStandardize)

    def test_fit_protocol_and_masks(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((4, 5, F))
        mask = np.zeros((4, 5), np.float32)
        mask[:, :3] = 1.0
        w = WindowedStandardize(window=8)
        w.fit(DataSet(x, None, mask))
        ref = NormalizerStandardize().fit(DataSet(x, None, mask))
        np.testing.assert_allclose(w.mean, ref.mean, rtol=1e-12)
        np.testing.assert_allclose(w.std, ref.std, rtol=1e-12)


# ===================================== publish listener online semantics
class TestPublishListenerOnline:
    def test_final_publish_at_off_cadence_fit_end(self, tmp_path):
        """Satellite regression: an online run stops at an arbitrary
        step — the final snapshot publishes from on_fit_end even when
        the stop iteration is off-cadence."""
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        listener = reg.publish_listener("m", frequency=100)
        net.add_listener(listener)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((7 * 4, F)).astype(np.float32)
        y = np.eye(C, dtype=np.float32)[rng.integers(0, C, 7 * 4)]
        net.fit(x, y, epochs=1, batch_size=4)      # 7 steps, cadence 100
        assert listener.published_versions == [1]
        assert listener.published_steps == [7]
        restored, _ = reg.resolve("m")
        assert params_equal(restored.params, net.params)

    def test_gate_pauses_without_advancing_cadence(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        allow = {"ok": True}
        listener = reg.publish_listener("m", frequency=3,
                                        gate=lambda: allow["ok"])
        # cadence boundary with the gate CLOSED: skipped, clock frozen
        allow["ok"] = False
        net.iteration_count = 3
        listener.iteration_done(net, 2, 0, 0.0)
        assert listener.published_versions == []
        assert listener.gated_skips == 1
        # gate reopens: the NEXT boundary publishes immediately (no
        # full fresh cadence owed)
        allow["ok"] = True
        net.iteration_count = 4
        listener.iteration_done(net, 3, 0, 0.0)
        assert listener.published_versions == [1]
        assert listener.published_steps == [4]

    def test_gated_skips_count_windows_not_iterations(self, tmp_path):
        """A closed gate makes EVERY step boundary overdue (the frozen
        clock); the skip counter must advance once per refused cadence
        window, not once per iteration."""
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        listener = reg.publish_listener("m", frequency=5,
                                        gate=lambda: False)
        for it in range(4, 14):            # steps 5..14, all overdue
            net.iteration_count = it + 1
            listener.iteration_done(net, it, 0, 0.0)
        # two refused windows (5 and 10), not ten refused iterations
        assert listener.gated_skips == 2

    def test_gate_applies_to_fit_end(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        listener = reg.publish_listener("m", frequency=100,
                                        gate=lambda: False)
        net.iteration_count = 9
        listener.on_fit_end(net)
        assert listener.published_versions == []
        assert listener.gated_skips == 1

    def test_cadence_anchors_at_warm_start(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        net.iteration_count = 200          # resumed / warm-started
        listener = reg.publish_listener("m", frequency=10)
        listener.on_fit_start(net)
        listener.iteration_done(net, 200, 0, 0.0)   # 1 new step only
        assert listener.published_versions == []
        listener.iteration_done(net, 209, 0, 0.0)   # 10 new steps
        assert listener.published_versions == [1]


# ================================================= drift gate integration
class TestDriftGate:
    def test_trip_and_recovery_through_real_evaluation(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            rng = np.random.default_rng(0)
            hx = rng.standard_normal((48, F)).astype(np.float32)
            hy = np.eye(C, dtype=np.float32)[
                np.argmax(hx @ _W_TRUE, axis=1)]
            heldout = DataSet(hx, hy)
            net = tiny_net()
            # train to decent held-out accuracy
            x = rng.standard_normal((40 * B, F)).astype(np.float32)
            y = np.eye(C, dtype=np.float32)[
                np.argmax(x @ _W_TRUE, axis=1)]
            net.fit(x, y, epochs=2, batch_size=B, shuffle=False)
            gate = DriftGate(heldout, frequency=1, band=0.2,
                             printer=lambda s: None)
            gate.iteration_done(net, 0, 0, 0.0)
            assert gate.best_score is not None and not gate.paused
            # corrupt the model -> held-out collapse -> trip
            good_params = jax.tree_util.tree_map(np.asarray, net.params)
            net.params = jax.tree_util.tree_map(
                lambda a: a * 0.0, net.params)
            gate.iteration_done(net, 1, 0, 0.0)
            assert gate.paused and gate.trips == 1
            assert not gate.allow_publish()
            # restore -> recovery reopens the gate
            import jax.numpy as jnp
            net.params = jax.tree_util.tree_map(jnp.asarray,
                                                good_params)
            gate.iteration_done(net, 2, 0, 0.0)
            assert not gate.paused and gate.allow_publish()
            assert gate.trips == 1
            snap = reg.snapshot()
            paused = snap["online_publish_paused"]["values"]
            assert any(e["value"] == 0.0 for e in paused)
            trips = snap["online_drift_trips_total"]["values"]
            assert any(e["value"] == 1 for e in trips)
            # the EvaluativeListener tap fed the score gauges too
            assert "evaluative_score" in snap
        finally:
            monitor.disable()


# ========================================================= OnlineTrainer
class TestOnlineTrainer:
    def test_stream_run_publishes_and_checkpoints(self, tmp_path):
        t = fill_log(make_records(30 * B, seed=11))
        it = make_stream(t)
        reg = ModelRegistry(tmp_path / "reg", keep_last=50)
        trainer = OnlineTrainer(
            tiny_net(), it, registry=reg, model_name="m",
            publish_frequency=10,
            checkpoint_dir=tmp_path / "ckpt", checkpoint_frequency=8)
        s = trainer.run(max_steps=24)
        assert s["iterations"] == 24
        # cadence publishes at 10, 20 + the off-cadence final at 24
        assert s["published_steps"] == [10, 20, 24]
        assert reg.versions("m") == [1, 2, 3]
        from deeplearning4j_tpu.fault.checkpointer import (
            list_checkpoints)
        steps = list_checkpoints(tmp_path / "ckpt")
        assert 8 in steps and 16 in steps and 24 in steps
        assert s["cursor"]["batch"] == 24

    def test_listeners_detach_after_run(self, tmp_path):
        t = fill_log(make_records(4 * B, seed=12))
        it = make_stream(t)
        net = tiny_net()
        n_before = len(net.listeners)
        OnlineTrainer(net, it, registry=ModelRegistry(tmp_path),
                      model_name="m", publish_frequency=100).run(
                          max_steps=2)
        assert len(net.listeners) == n_before

    def test_run_twice_over_the_same_iterator(self, tmp_path):
        """max_steps ends a run by stopping the ITERATOR; the stop flag
        is per-pass, so a second run() continues the stream instead of
        silently training zero steps."""
        t = fill_log(make_records(10 * B, seed=17))
        it = make_stream(t)
        trainer = OnlineTrainer(tiny_net(), it)
        assert trainer.run(max_steps=3)["iterations"] == 3
        s = trainer.run(max_steps=4)
        assert s["iterations"] == 4
        assert it.cursor()["batch"] == 7

    def test_windowed_normalizer_wires_into_stream(self, tmp_path):
        t = fill_log(make_records(6 * B, seed=13))
        w = WindowedStandardize(window=4)
        it = make_stream(t, normalizer=None)
        reg = ModelRegistry(tmp_path)
        trainer = OnlineTrainer(tiny_net(), it, registry=reg,
                                model_name="m", publish_frequency=3,
                                normalizer=w)
        assert it.normalizer is w
        s = trainer.run(max_steps=6)
        assert w.records_seen == 6 * B
        # every published zip carries the snapshot of ITS window
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        versions = s["published_versions"]
        assert len(versions) >= 2
        snaps = [ModelSerializer.restore_normalizer_from_file(
            reg.path("m", v)) for v in versions]
        assert [s_.version for s_ in snaps] == \
            list(range(1, len(versions) + 1))
        # later snapshots saw more records (the watermark advanced)
        assert snaps[-1].records_seen > snaps[0].records_seen


# ===================================== resume-from-offset bit-parity
class TestResumeFromOffsetParity:
    def test_interrupted_run_resumes_bit_equal(self, tmp_path):
        """Satellite: interrupt an OnlineTrainer mid-stream, resume via
        fault/ with the transport replayed from the checkpoint cursor —
        trajectory bit-equality with an uninterrupted run over the same
        record sequence."""
        records = make_records(24 * B, seed=14)
        total = 24

        # --- reference: uninterrupted
        tA = fill_log(records)
        scoresA = CollectScoresListener()
        netA = tiny_net()
        netA.add_listener(scoresA)
        OnlineTrainer(netA, make_stream(tA)).run(max_steps=total)

        # --- interrupted at 16; newest checkpoint is MID-STREAM at 12
        tB = fill_log(records)
        netB = tiny_net()
        OnlineTrainer(netB, make_stream(tB),
                      checkpoint_dir=tmp_path,
                      checkpoint_frequency=12,
                      checkpoint_at_fit_end=False).run(max_steps=16)
        del netB          # the "kill": nothing survives but the ckpt

        # --- resume: fresh everything, transport replayed from offset
        tC = fill_log(records)
        itC = make_stream(tC)
        trC = OnlineTrainer.resume(tmp_path, itC)
        assert trC.net.iteration_count == 12
        assert itC.cursor() == {"kind": "stream", "topic": "train",
                                "batch": 12, "batch_size": B,
                                "offset": 12 * B}
        scoresC = CollectScoresListener()
        trC.net.add_listener(scoresC)
        trC.run(max_steps=total - 12)
        assert trC.net.iteration_count == total

        # params bit-equal AND the post-resume score trajectory
        # bit-equal to the reference's same steps (12..23): batches
        # 12..15 — trained by the interrupted run after its last
        # checkpoint — replayed from the offset, not skipped
        assert params_equal(netA.params, trC.net.params)
        refA = {it: s for it, s in scoresA.scores}
        for it, s in scoresC.scores:
            assert s == refA[it], (it, s, refA[it])

    def test_resume_restores_live_normalizer_window(self, tmp_path):
        records = make_records(16 * B, seed=15)
        tA = fill_log(records)
        wA = WindowedStandardize(window=3)
        netA = tiny_net()
        OnlineTrainer(netA, make_stream(tA),
                      normalizer=wA).run(max_steps=12)

        tB = fill_log(records)
        wB = WindowedStandardize(window=3)
        OnlineTrainer(tiny_net(), make_stream(tB), normalizer=wB,
                      checkpoint_dir=tmp_path, checkpoint_frequency=6,
                      checkpoint_at_fit_end=False).run(max_steps=9)
        tC = fill_log(records)
        itC = make_stream(tC)
        trC = OnlineTrainer.resume(tmp_path, itC)
        # the restored WINDOW (not just aggregate) resumed at step 6;
        # replaying to 12 reproduces the reference stats bit-exactly
        assert isinstance(trC.normalizer, WindowedStandardize)
        assert itC.normalizer is trC.normalizer
        trC.run(max_steps=6)
        np.testing.assert_array_equal(trC.normalizer.mean, wA.mean)
        np.testing.assert_array_equal(trC.normalizer.std, wA.std)
        assert params_equal(netA.params, trC.net.params)


# ============================================= /train staleness row (UI)
class TestStreamingUI:
    def test_overview_renders_staleness_row(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            t = fill_log(make_records(2 * B, seed=16), topic="ui")
            list(make_stream(t, topic="ui"))
            reg.counter("online_publishes_total",
                        help="", model="m").inc(3)
            reg.gauge("online_publish_paused", help="",
                      tag="heldout").set(0.0)
            import urllib.request

            from deeplearning4j_tpu.ui import UIServer
            server = UIServer().start()
            try:
                html = urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/train/overview",
                    timeout=10).read().decode()
                assert "streaming / online training" in html
                assert "ui" in html and "records consumed" in html
                # separate attribution rows: per-model publishes and
                # per-tag gate state (no cross-topic smearing)
                assert "model m" in html
                assert "gate heldout" in html
                assert "open" in html         # gate not paused
            finally:
                server.stop()
        finally:
            monitor.disable()


# ================================================ drift gate: loss band
class TestDriftGateLossBand:
    """Satellite: `metric="loss"` gates on held-out LOSS rising past
    ``best + band`` — the regression / LM-perplexity form of the gate,
    where accuracy means nothing."""

    def _heldout(self, seed=0, n=48):
        rng = np.random.default_rng(seed)
        hx = rng.standard_normal((n, F)).astype(np.float32)
        hy = np.eye(C, dtype=np.float32)[np.argmax(hx @ _W_TRUE, axis=1)]
        return DataSet(hx, hy)

    def test_trip_on_loss_rise_and_recovery(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            heldout = self._heldout()
            net = tiny_net()
            rng = np.random.default_rng(1)
            x = rng.standard_normal((40 * B, F)).astype(np.float32)
            y = np.eye(C, dtype=np.float32)[np.argmax(x @ _W_TRUE, axis=1)]
            net.fit(x, y, epochs=2, batch_size=B, shuffle=False)
            gate = DriftGate(heldout, frequency=1, band=0.3,
                             metric="loss", printer=lambda s: None)
            gate.iteration_done(net, 0, 0, 0.0)
            assert gate.best_score is not None and not gate.paused
            base_loss = gate.best_score
            # corrupt the model -> held-out loss EXPLODES -> trip
            good_params = jax.tree_util.tree_map(np.asarray, net.params)
            net.params = jax.tree_util.tree_map(
                lambda a: a * 17.0, net.params)
            gate.iteration_done(net, 1, 0, 0.0)
            assert gate.paused and gate.trips == 1
            assert gate.last_score > base_loss + 0.3
            assert not gate.allow_publish()
            # best tracked the MINIMUM, not the latest
            assert gate.best_score == base_loss
            import jax.numpy as jnp
            net.params = jax.tree_util.tree_map(jnp.asarray, good_params)
            gate.iteration_done(net, 2, 0, 0.0)
            assert not gate.paused and gate.allow_publish()
            snap = reg.snapshot()
            scores = snap["evaluative_score"]["values"]
            assert any(e["labels"].get("metric") == "loss"
                       for e in scores)
        finally:
            monitor.disable()

    def test_loss_gate_drives_publish_listener(self, tmp_path):
        """End to end: the loss gate refuses a degraded publish and
        reopens after recovery — wired exactly like the accuracy
        gate."""
        registry = ModelRegistry(tmp_path)
        heldout = self._heldout()
        net = tiny_net()
        gate = DriftGate(heldout, frequency=1, band=0.5, metric="loss",
                         printer=lambda s: None)
        listener = registry.publish_listener("m", frequency=2,
                                             gate=gate.allow_publish)
        gate.iteration_done(net, 0, 0, 0.0)
        net.iteration_count = 2
        listener.iteration_done(net, 1, 0, 0.0)
        assert listener.published_versions == [1]
        net.params = jax.tree_util.tree_map(lambda a: a * 29.0,
                                            net.params)
        gate.iteration_done(net, 2, 0, 0.0)
        assert gate.paused
        net.iteration_count = 4
        listener.iteration_done(net, 3, 0, 0.0)
        assert listener.published_versions == [1]   # refused
        assert listener.gated_skips == 1

    def test_unknown_metric_rejected(self):
        with pytest.raises(ValueError, match="metric"):
            DriftGate(self._heldout(), metric="auc")


# ====================================== publish listener: wall-clock cadence
class TestPublishListenerEveryS:
    def test_clock_publishes_regardless_of_step_cadence(self, tmp_path):
        """frequency too high to ever fire; the wall clock alone must
        publish — "a fresh model every N seconds regardless of
        throughput"."""
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        # 0.4 s period: the not-yet-due assertions tolerate hundreds
        # of ms of incidental work (zip publish, loaded CI core)
        # between calls without going flaky
        listener = reg.publish_listener("m", frequency=10_000,
                                        every_s=0.4)
        listener.on_fit_start(net)          # anchors the clock
        net.iteration_count = 1
        listener.iteration_done(net, 0, 0, 0.0)
        assert listener.published_versions == []    # period not yet up
        time.sleep(0.45)
        net.iteration_count = 2
        listener.iteration_done(net, 1, 0, 0.0)
        assert listener.published_versions == [1]
        # the clock re-arms at the publish: the very next boundary is
        # NOT due again
        net.iteration_count = 3
        listener.iteration_done(net, 2, 0, 0.0)
        assert listener.published_versions == [1]
        time.sleep(0.45)
        net.iteration_count = 4
        listener.iteration_done(net, 3, 0, 0.0)
        assert listener.published_versions == [1, 2]

    def test_step_cadence_still_applies_alongside(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        listener = reg.publish_listener("m", frequency=2,
                                        every_s=3600.0)
        listener.on_fit_start(net)
        net.iteration_count = 2
        listener.iteration_done(net, 1, 0, 0.0)   # 2 steps -> due
        assert listener.published_versions == [1]

    def test_gate_refusal_freezes_the_clock(self, tmp_path):
        """A refused clock publish does NOT advance the clock: the
        first boundary after the gate reopens publishes immediately."""
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        allow = {"ok": False}
        listener = reg.publish_listener("m", frequency=10_000,
                                        every_s=0.2,
                                        gate=lambda: allow["ok"])
        listener.on_fit_start(net)
        time.sleep(0.25)
        net.iteration_count = 1
        listener.iteration_done(net, 0, 0, 0.0)
        assert listener.published_versions == []    # refused
        allow["ok"] = True
        net.iteration_count = 2
        listener.iteration_done(net, 1, 0, 0.0)     # still overdue
        assert listener.published_versions == [1]

    def test_off_cadence_fit_end_publish_preserved(self, tmp_path):
        """every_s must not break the fit-end off-cadence publish
        contract (nor fire when nothing new trained)."""
        reg = ModelRegistry(tmp_path)
        net = tiny_net()
        listener = reg.publish_listener("m", frequency=10_000,
                                        every_s=3600.0)
        net.add_listener(listener)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((7 * 4, F)).astype(np.float32)
        y = np.eye(C, dtype=np.float32)[rng.integers(0, C, 7 * 4)]
        net.fit(x, y, epochs=1, batch_size=4)
        assert listener.published_versions == [1]   # on_fit_end only
        assert listener.published_steps == [7]

    def test_invalid_every_s_rejected(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        with pytest.raises(ValueError, match="every_s"):
            reg.publish_listener("m", every_s=0)
