"""Serving tier: paged KV-cache pool + continuous-batching scheduler.

The decode-parity contract (docs/SERVING.md) is the spine of this
suite: continuous-batched decode must emit EXACTLY the tokens
whole-batch `generate()` emits — greedy bit-equal — including
sequences that join/leave mid-stream, blocks that get freed and
reused, and pools too small to hold every request at once.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.serving import (
    GARBAGE_BLOCK,
    BlockAllocator,
    GenerationServer,
    PagedDecodeEngine,
    ShedError,
    blocks_needed,
)
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 16
BL = 4          # block_len; MAXLEN/BL = 4 blocks per full sequence


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net():
    return tiny_lm()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 3))


@pytest.fixture(scope="module")
def ref_tokens(net, prompts):
    return generate(net, prompts, 6, temperature=0)     # [6, 6]


def drain_engine(eng, slot2req, out):
    """Step until idle, routing emissions into `out[request]`."""
    guard = 0
    while eng.active.any():
        emitted, finished = eng.step()
        for slot, toks in emitted.items():
            out[slot2req[slot]].extend(toks)
        for slot in finished:
            del slot2req[slot]
        guard += 1
        assert guard < 200, "engine failed to drain"


class TestBlockAllocator:
    def test_allocate_free_cycle(self):
        a = BlockAllocator(8)            # 7 usable, id 0 reserved
        assert a.free_blocks == 7
        got = a.allocate(3)
        assert got is not None and len(got) == 3
        assert GARBAGE_BLOCK not in got
        assert a.allocate(5) is None     # all-or-nothing
        assert a.free_blocks == 4
        a.free(got)
        assert a.free_blocks == 7

    def test_double_free_and_bad_ids_rejected(self):
        a = BlockAllocator(4)
        got = a.allocate(1)
        a.free(got)
        with pytest.raises(ValueError, match="double-free"):
            a.free(got)
        with pytest.raises(ValueError, match="invalid block"):
            a.free([0])

    def test_blocks_needed(self):
        assert blocks_needed(1, 4) == 1
        assert blocks_needed(4, 4) == 1
        assert blocks_needed(5, 4) == 2


class TestPagedAttentionParity:
    def test_paged_block_matches_monolithic_carry(self, net):
        """Stepwise: the paged path (non-contiguous blocks, garbage in
        every unowned page) must be BIT-equal to the monolithic KV
        carry — the property the serving parity contract rests on."""
        blk_i = 2     # first encoder block in the stack
        blk = net.layers[blk_i]
        params = net.params[str(blk_i)]
        rng = np.random.default_rng(0)
        B, N = 2, 12
        shape = (N, BL, HEADS, D // HEADS)
        k_pool = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        bt = jnp.asarray([[3, 5, 7, 9], [2, 4, 6, 8]], jnp.int32)
        pos = jnp.zeros(B, jnp.int32)
        carry = blk.init_carry(B, jnp.float32)
        for _ in range(5):
            x = jnp.asarray(rng.standard_normal((B, 1, D)), jnp.float32)
            y_mono, _, carry = blk.forward_with_carry(
                params, {}, x, carry)
            y_paged, k_pool, v_pool = blk.forward_paged(
                params, x, k_pool, v_pool, bt, pos)
            pos = pos + 1
            np.testing.assert_array_equal(np.asarray(y_mono),
                                          np.asarray(y_paged))

    def test_positional_at_positions_matches_carry(self, net):
        pe = net.layers[1]
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((3, 1, D)), jnp.float32)
        for p in (0, 3, 9):
            want, _, _ = pe.forward_with_carry(
                {}, {}, x[:1], jnp.asarray(p, jnp.int32))
            got, _ = pe.forward_at_positions(
                {}, {}, x[:1], jnp.asarray([p], jnp.int32))
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got))


class TestEngineGreedyParity:
    def test_staggered_admissions_bit_equal(self, net, prompts,
                                            ref_tokens):
        """2 slots, 4 requests: sequences join as others finish —
        every stream must match its whole-batch generate() row."""
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        out = {r: [] for r in range(4)}
        slot2req = {}
        pending = list(range(4))
        guard = 0
        while pending or eng.active.any():
            while pending and eng.can_admit(prompts.shape[1], 6):
                r = pending.pop(0)
                (slot, first, done), = eng.admit_many(
                    [dict(prompt_ids=prompts[r], n_tokens=6)])
                out[r].append(first)
                if not done:
                    slot2req[slot] = r
            emitted, finished = eng.step()
            for slot, toks in emitted.items():
                out[slot2req[slot]].extend(toks)
            for slot in finished:
                del slot2req[slot]
            guard += 1
            assert guard < 100
        got = np.asarray([out[r] for r in range(4)])
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_chunked_dispatch_same_tokens(self, net, prompts,
                                          ref_tokens):
        """steps_per_dispatch > 1 (fused micro-step scan) emits the
        same streams as one-token-per-dispatch, including a slot
        finishing mid-chunk (6 tokens, J=4 -> 2nd chunk half-valid)."""
        for J in (4, 8):
            eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                    block_len=BL, steps_per_dispatch=J)
            out = {r: [] for r in range(4)}
            slot2req = {}
            admitted = eng.admit_many(
                [dict(prompt_ids=prompts[r], n_tokens=6)
                 for r in range(4)])
            for r, (slot, first, done) in enumerate(admitted):
                out[r].append(first)
                if not done:
                    slot2req[slot] = r
            drain_engine(eng, slot2req, out)
            got = np.asarray([out[r] for r in range(4)])
            np.testing.assert_array_equal(got, ref_tokens[:4], err_msg=f"J={J}")

    def test_evict_readmit_reuses_blocks_correctly(self, net, prompts,
                                                   ref_tokens):
        """Mid-stream eviction frees blocks; a new sequence admitted
        into those SAME pool blocks must decode exactly (the freed
        pages' stale content is dead weight, not state)."""
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=4,
                                block_len=BL)   # 3 usable blocks
        (slot, first, done), = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=6)])
        blocks_first = list(eng.slots[slot].blocks)
        eng.step()
        eng.evict(slot)                  # mid-stream cancel
        assert eng.free_blocks == 3
        # readmit a DIFFERENT request: must land on the same block ids
        (slot2, first2, _), = eng.admit_many(
            [dict(prompt_ids=prompts[1], n_tokens=6)])
        assert set(eng.slots[slot2].blocks) & set(blocks_first), \
            "allocator did not reuse the freed blocks"
        out = {1: [first2]}
        drain_engine(eng, {slot2: 1}, out)
        np.testing.assert_array_equal(np.asarray(out[1]), ref_tokens[1])

    def test_admission_wave_batched_prefill_parity(self, net, prompts,
                                                   ref_tokens):
        """A k>1 admission wave (one batched prefill + one fused
        page-write/first-token dispatch) admits every request with the
        same tokens as separate k=1 admissions."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                block_len=BL)
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[r], n_tokens=6) for r in range(4)])
        assert len(admitted) == 4
        out = {r: [admitted[r][1]] for r in range(4)}
        drain_engine(eng, {admitted[r][0]: r for r in range(4)}, out)
        got = np.asarray([out[r] for r in range(4)])
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_pool_exhaustion_admits_prefix_only(self, net, prompts):
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=7,
                                block_len=BL)   # 6 usable = 2 seqs
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[r], n_tokens=6) for r in range(4)])
        assert len(admitted) == 2
        assert eng.free_blocks == 0

    def test_budget_rejected_eagerly(self, net):
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        with pytest.raises(ValueError, match="page budget"):
            eng.check_budget(10, 10)    # 20 > 16
        with pytest.raises(ValueError, match="must divide"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=5)


class TestSampledDeterminism:
    def test_same_stream_alone_or_batched(self, net, prompts):
        """The serving rng contract: token t of a request derives from
        fold_in(request_key, t) — the stream must not depend on what
        else is in flight (whole-batch generate() cannot offer this;
        the serving tier guarantees it)."""
        key = np.asarray([7, 9], np.uint32)

        def run(extra):
            eng = PagedDecodeEngine(net, n_slots=4, n_blocks=24,
                                    block_len=BL)
            reqs = [dict(prompt_ids=prompts[0], n_tokens=6,
                         temperature=1.0, top_p=0.9, rng=key)]
            for e in range(extra):
                reqs.append(dict(prompt_ids=prompts[e + 1], n_tokens=6,
                                 temperature=0.7,
                                 rng=np.asarray([e, e], np.uint32)))
            admitted = eng.admit_many(reqs)
            out = {r: [admitted[r][1]] for r in range(len(reqs))}
            drain_engine(
                eng, {admitted[r][0]: r for r in range(len(reqs))}, out)
            return out[0]

        alone = run(0)
        batched = run(3)
        assert alone == batched
        assert all(0 <= t < V for t in alone)

    def test_greedy_and_sampled_mix_keeps_greedy_exact(self, net,
                                                       prompts,
                                                       ref_tokens):
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[0], n_tokens=6),    # greedy
            dict(prompt_ids=prompts[1], n_tokens=6, temperature=1.0,
                 rng=np.asarray([1, 2], np.uint32)),
        ])
        out = {r: [admitted[r][1]] for r in range(2)}
        drain_engine(eng, {admitted[r][0]: r for r in range(2)}, out)
        np.testing.assert_array_equal(np.asarray(out[0]), ref_tokens[0])


class TestGenerationServer:
    def test_concurrent_streams_greedy_parity(self, net, prompts,
                                              ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(6)]
            got = np.stack([s.result(timeout=120) for s in streams])
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens)

    def test_iterator_streams_tokens_incrementally(self, net, prompts,
                                                   ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            toks = list(srv.generate_async(prompts[0], 6))
        finally:
            srv.stop()
        assert toks == list(ref_tokens[0])

    def test_pool_exhaustion_queues_not_corrupts(self, net, prompts,
                                                 ref_tokens):
        """Pool holds ONE sequence: 4 concurrent requests must all
        complete exactly (later ones wait for blocks; nothing reads
        another sequence's pages)."""
        srv = GenerationServer(net, n_slots=4, n_blocks=4,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(4)]
            got = np.stack([s.result(timeout=120) for s in streams])
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_cancel_midstream_and_while_queued(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=5,
                               block_len=BL,
                               steps_per_dispatch=1).start()
        try:
            # A holds the only slot; B is necessarily still queued
            # (pool fits ONE sequence) — cancelling B must retire it
            # without it ever touching a slot
            a = srv.generate_async(prompts[0], 12)
            b = srv.generate_async(prompts[1], 12)
            it = iter(a)
            first = next(it)
            b.cancel()
            a.cancel()                       # mid-stream (best effort)
            got = [first] + list(it)
            assert 1 <= len(got) <= 12
            assert list(a.result(timeout=30)) == got
            assert list(b.result(timeout=30)) == []
            # slot + blocks are free again: a new request runs fully
            s2 = srv.generate_async(prompts[2], 6)
            assert len(s2.result(timeout=120)) == 6
        finally:
            srv.stop()

    def test_shed_under_overload(self, net, prompts):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=1, n_blocks=4,
                               block_len=BL, max_queue=1,
                               slo_ttft_s=1e-3).start()
        try:
            streams = [srv.generate_async(prompts[r % 6], 6)
                       for r in range(8)]
            shed = ok = 0
            for s in streams:
                try:
                    s.result(timeout=120)
                    ok += 1
                except ShedError:
                    shed += 1
        finally:
            srv.stop()
            monitor.disable()
        assert shed >= 1 and ok >= 1
        assert reg.counter("serving_shed_total").value == shed

    def test_serving_metrics_families(self, net, prompts):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(3)]
            for s in streams:
                s.result(timeout=120)
            deadline = time.monotonic() + 5
            while (reg.timer("serving_tpot_seconds").count < 3
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            srv.stop()
            monitor.disable()
        assert reg.counter("serving_requests_total").value == 3
        assert reg.counter("serving_tokens_total").value == 18
        assert reg.timer("serving_ttft_seconds").count == 3
        assert reg.timer("serving_tpot_seconds").count == 3
        assert reg.counter("serving_shed_total").value == 0
        exposition = reg.exposition()
        for fam in ("serving_queue_depth", "serving_active_slots",
                    "serving_free_blocks", "serving_ttft_seconds"):
            assert fam in exposition

    def test_stop_fails_inflight_and_queued(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=4,
                               block_len=BL).start()
        streams = [srv.generate_async(prompts[r % 6], 6)
                   for r in range(4)]
        srv.stop()
        outcomes = []
        for s in streams:
            try:
                s.result(timeout=10)
                outcomes.append("ok")
            except RuntimeError:
                outcomes.append("failed")
        # nothing may HANG; at least the queued tail must have failed
        assert len(outcomes) == 4 and "failed" in outcomes

    def test_validation_eager(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=8, block_len=BL)
        with pytest.raises(RuntimeError, match="start"):
            srv.generate_async(prompts[0], 6)
        srv.start()
        try:
            with pytest.raises(ValueError, match="page budget"):
                srv.generate_async(prompts[0], MAXLEN + 1)
            # within the page budget but needing more blocks than the
            # whole pool owns: must fail at submit, not deadlock queued
            small = GenerationServer(net, n_slots=1, n_blocks=3,
                                     block_len=BL)
            with pytest.raises(ValueError, match="never be admitted"):
                small.engine.check_budget(3, 12)   # 4 blocks > 2 usable
            with pytest.raises(ValueError, match="top_p"):
                srv.generate_async(prompts[0], 4, top_p=0.0)
            with pytest.raises(ValueError, match="non-empty"):
                srv.generate_async(np.zeros((0,), np.int32), 4)
        finally:
            srv.stop()

    def test_warmup_compiles_before_start(self, net, prompts,
                                          ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL)
        srv.warmup(prompts.shape[1], 6).start()
        try:
            got = srv.generate_async(prompts[0], 6).result(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens[0])
        with pytest.raises(RuntimeError, match="before start"):
            GenerationServer(net, n_slots=2, n_blocks=16,
                             block_len=BL).start().warmup(3)


class TestServingBenchGate:
    def test_compare_bench_gates_serving_metrics(self):
        from deeplearning4j_tpu.bench import compare_bench

        def rec(tps, speedup):
            return {"platform": "cpu-sandbox", "value": 100.0,
                    "extras": {"serving": {
                        "tokens_per_sec": tps,
                        "speedup_vs_sequential": speedup}}}

        base = rec(5000.0, 1.5)
        assert compare_bench(rec(4900.0, 1.45), base)["status"] == "pass"
        verdict = compare_bench(rec(2000.0, 1.5), base)
        assert verdict["status"] == "regression"
        assert any(r["metric"] == "serving_tokens_per_sec"
                   for r in verdict["regressions"])
        verdict = compare_bench(rec(5000.0, 0.9), base)
        assert verdict["status"] == "regression"
        assert any(r["metric"] == "serving_speedup_vs_sequential"
                   for r in verdict["regressions"])


class TestServingUI:
    def test_serving_page_renders_registry_state(self, net, prompts):
        import urllib.request

        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui import UIServer

        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            srv.generate_async(prompts[0], 6).result(timeout=120)
        finally:
            srv.stop()
            monitor.disable()
        ui = UIServer(registry=reg).start()
        try:
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(base + "/serving",
                                          timeout=10).read().decode()
            assert "requests admitted" in html
            assert "free pool blocks" in html
            mtext = urllib.request.urlopen(base + "/metrics",
                                           timeout=10).read().decode()
            assert "serving_ttft_seconds" in mtext
        finally:
            ui.stop()


class TestReviewHardening:
    def test_midwave_failure_returns_allocated_blocks(self, net,
                                                      prompts):
        """A wave interrupted AFTER earlier requests' blocks were
        allocated (here: a later request failing validation) must
        return them to the pool — no Slot owns them yet, so nothing
        else ever could (the capacity-leak -> silent-starvation
        failure)."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                block_len=BL)
        before = eng.free_blocks
        with pytest.raises(ValueError, match="non-empty"):
            eng.admit_many([
                dict(prompt_ids=prompts[0], n_tokens=6),
                dict(prompt_ids=np.zeros((0,), np.int32), n_tokens=6),
            ])
        assert eng.free_blocks == before, "mid-wave failure leaked blocks"
        # pool still fully serviceable
        admitted = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=6)])
        assert len(admitted) == 1

    def test_output_async_refused_on_generation_server(self, net):
        srv = GenerationServer(net, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        try:
            with pytest.raises(NotImplementedError, match="generate_async"):
                srv.output_async(np.zeros((1, 3), np.float32))
        finally:
            srv.stop()

    def test_warmup_covers_sampled_decode_program(self, net):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL)
        srv.warmup(3)
        assert srv.engine._decode_greedy is not None
        assert srv.engine._decode_full is not None, (
            "warmup left the sampled decode program uncompiled — the "
            "first temperature>0 request would stall live streams")

    def test_default_sampled_requests_draw_distinct_streams(self, net,
                                                            prompts):
        """rng=None + temperature>0 must NOT collapse onto the
        engine's deterministic zero key: two concurrent no-rng sampled
        requests for the SAME prompt get distinct streams (pass rng
        explicitly for reproducibility)."""
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            a = srv.generate_async(prompts[0], 8, temperature=1.0)
            b = srv.generate_async(prompts[0], 8, temperature=1.0)
            ta = list(a.result(timeout=120))
            tb = list(b.result(timeout=120))
        finally:
            srv.stop()
        assert ta != tb, "no-rng sampled requests shared one key"

    def test_cancelled_queued_requests_do_not_shed_fresh_ones(self, net,
                                                              prompts):
        """Cancelled entries stranded mid-queue must stop counting
        toward max_queue / the shed projection — phantom load must not
        shed real requests."""
        srv = GenerationServer(net, n_slots=1, n_blocks=5,
                               block_len=BL, max_queue=2,
                               steps_per_dispatch=1).start()
        try:
            a = srv.generate_async(prompts[0], 12)   # holds the slot
            queued = [srv.generate_async(prompts[1], 6)
                      for _ in range(2)]             # fills max_queue
            for s in queued:
                s.cancel()
            # give the scheduler a beat to reap the cancelled entries
            for s in queued:
                s.result(timeout=30)
            fresh = srv.generate_async(prompts[2], 6)
            got = fresh.result(timeout=120)          # must NOT ShedError
            assert len(got) == 6
            a.result(timeout=120)
        finally:
            srv.stop()
