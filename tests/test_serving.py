"""Serving tier: paged KV-cache pool + continuous-batching scheduler.

The decode-parity contract (docs/SERVING.md) is the spine of this
suite: continuous-batched decode must emit EXACTLY the tokens
whole-batch `generate()` emits — greedy bit-equal — including
sequences that join/leave mid-stream, blocks that get freed and
reused, and pools too small to hold every request at once.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.serving import (
    GARBAGE_BLOCK,
    BlockAllocator,
    GenerationServer,
    PagedDecodeEngine,
    ShedError,
    blocks_needed,
)
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 16
BL = 4          # block_len; MAXLEN/BL = 4 blocks per full sequence


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net():
    return tiny_lm()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 3))


@pytest.fixture(scope="module")
def ref_tokens(net, prompts):
    return generate(net, prompts, 6, temperature=0)     # [6, 6]


def drain_engine(eng, slot2req, out):
    """Step until idle, routing emissions into `out[request]`."""
    guard = 0
    while eng.active.any():
        emitted, finished = eng.step()
        for slot, toks in emitted.items():
            out[slot2req[slot]].extend(toks)
        for slot in finished:
            del slot2req[slot]
        guard += 1
        assert guard < 200, "engine failed to drain"


class TestBlockAllocator:
    def test_allocate_free_cycle(self):
        a = BlockAllocator(8)            # 7 usable, id 0 reserved
        assert a.free_blocks == 7
        got = a.allocate(3)
        assert got is not None and len(got) == 3
        assert GARBAGE_BLOCK not in got
        assert a.allocate(5) is None     # all-or-nothing
        assert a.free_blocks == 4
        a.free(got)
        assert a.free_blocks == 7

    def test_double_free_and_bad_ids_rejected(self):
        a = BlockAllocator(4)
        got = a.allocate(1)
        a.free(got)
        with pytest.raises(ValueError, match="double-free"):
            a.free(got)
        with pytest.raises(ValueError, match="invalid block"):
            a.free([0])

    def test_blocks_needed(self):
        assert blocks_needed(1, 4) == 1
        assert blocks_needed(4, 4) == 1
        assert blocks_needed(5, 4) == 2


class TestPagedAttentionParity:
    def test_paged_block_matches_monolithic_carry(self, net):
        """Stepwise: the paged path (non-contiguous blocks, garbage in
        every unowned page) must be BIT-equal to the monolithic KV
        carry — the property the serving parity contract rests on."""
        blk_i = 2     # first encoder block in the stack
        blk = net.layers[blk_i]
        params = net.params[str(blk_i)]
        rng = np.random.default_rng(0)
        B, N = 2, 12
        shape = (N, BL, HEADS, D // HEADS)
        k_pool = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        bt = jnp.asarray([[3, 5, 7, 9], [2, 4, 6, 8]], jnp.int32)
        pos = jnp.zeros(B, jnp.int32)
        carry = blk.init_carry(B, jnp.float32)
        for _ in range(5):
            x = jnp.asarray(rng.standard_normal((B, 1, D)), jnp.float32)
            y_mono, _, carry = blk.forward_with_carry(
                params, {}, x, carry)
            y_paged, k_pool, v_pool = blk.forward_paged(
                params, x, k_pool, v_pool, bt, pos)
            pos = pos + 1
            np.testing.assert_array_equal(np.asarray(y_mono),
                                          np.asarray(y_paged))

    def test_positional_at_positions_matches_carry(self, net):
        pe = net.layers[1]
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((3, 1, D)), jnp.float32)
        for p in (0, 3, 9):
            want, _, _ = pe.forward_with_carry(
                {}, {}, x[:1], jnp.asarray(p, jnp.int32))
            got, _ = pe.forward_at_positions(
                {}, {}, x[:1], jnp.asarray([p], jnp.int32))
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got))


class TestEngineGreedyParity:
    def test_staggered_admissions_bit_equal(self, net, prompts,
                                            ref_tokens):
        """2 slots, 4 requests: sequences join as others finish —
        every stream must match its whole-batch generate() row."""
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        out = {r: [] for r in range(4)}
        slot2req = {}
        pending = list(range(4))
        guard = 0
        while pending or eng.active.any():
            while pending and eng.can_admit(prompts.shape[1], 6):
                r = pending.pop(0)
                (slot, first, done), = eng.admit_many(
                    [dict(prompt_ids=prompts[r], n_tokens=6)])
                out[r].append(first)
                if not done:
                    slot2req[slot] = r
            emitted, finished = eng.step()
            for slot, toks in emitted.items():
                out[slot2req[slot]].extend(toks)
            for slot in finished:
                del slot2req[slot]
            guard += 1
            assert guard < 100
        got = np.asarray([out[r] for r in range(4)])
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_chunked_dispatch_same_tokens(self, net, prompts,
                                          ref_tokens):
        """steps_per_dispatch > 1 (fused micro-step scan) emits the
        same streams as one-token-per-dispatch, including a slot
        finishing mid-chunk (6 tokens, J=4 -> 2nd chunk half-valid)."""
        for J in (4, 8):
            eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                    block_len=BL, steps_per_dispatch=J)
            out = {r: [] for r in range(4)}
            slot2req = {}
            admitted = eng.admit_many(
                [dict(prompt_ids=prompts[r], n_tokens=6)
                 for r in range(4)])
            for r, (slot, first, done) in enumerate(admitted):
                out[r].append(first)
                if not done:
                    slot2req[slot] = r
            drain_engine(eng, slot2req, out)
            got = np.asarray([out[r] for r in range(4)])
            np.testing.assert_array_equal(got, ref_tokens[:4], err_msg=f"J={J}")

    def test_evict_readmit_reuses_blocks_correctly(self, net, prompts,
                                                   ref_tokens):
        """Mid-stream eviction frees blocks; a new sequence admitted
        into those SAME pool blocks must decode exactly (the freed
        pages' stale content is dead weight, not state)."""
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=4,
                                block_len=BL)   # 3 usable blocks
        (slot, first, done), = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=6)])
        blocks_first = list(eng.slots[slot].blocks)
        eng.step()
        eng.evict(slot)                  # mid-stream cancel
        assert eng.free_blocks == 3
        # readmit a DIFFERENT request: must land on the same block ids
        (slot2, first2, _), = eng.admit_many(
            [dict(prompt_ids=prompts[1], n_tokens=6)])
        assert set(eng.slots[slot2].blocks) & set(blocks_first), \
            "allocator did not reuse the freed blocks"
        out = {1: [first2]}
        drain_engine(eng, {slot2: 1}, out)
        np.testing.assert_array_equal(np.asarray(out[1]), ref_tokens[1])

    def test_admission_wave_batched_prefill_parity(self, net, prompts,
                                                   ref_tokens):
        """A k>1 admission wave (one batched prefill + one fused
        page-write/first-token dispatch) admits every request with the
        same tokens as separate k=1 admissions."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                block_len=BL)
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[r], n_tokens=6) for r in range(4)])
        assert len(admitted) == 4
        out = {r: [admitted[r][1]] for r in range(4)}
        drain_engine(eng, {admitted[r][0]: r for r in range(4)}, out)
        got = np.asarray([out[r] for r in range(4)])
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_pool_exhaustion_admits_prefix_only(self, net, prompts):
        # upfront (the PR-9 policy): each request reserves its FULL
        # 9-token budget = 3 blocks -> 6 usable blocks admit only 2
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=7,
                                block_len=BL, allocation="upfront")
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[r], n_tokens=6) for r in range(4)])
        assert len(admitted) == 2
        assert eng.free_blocks == 0
        # incremental (default): admission grants only the PROMPT
        # footprint (3 tokens = 1 block) — the same pool admits all 4
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=7,
                                block_len=BL)
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[r], n_tokens=6) for r in range(4)])
        assert len(admitted) == 4
        assert eng.free_blocks == 2

    def test_budget_rejected_eagerly(self, net):
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        with pytest.raises(ValueError, match="page budget"):
            eng.check_budget(10, 10)    # 20 > 16
        with pytest.raises(ValueError, match="must divide"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=5)


class TestIncrementalAllocation:
    """allocation="incremental" (the default): admission grants only
    the PROMPT footprint, `step()` grows block tables lazily as writes
    cross block boundaries, and pool pressure preempts-and-requeues the
    lowest-progress slot instead of deadlocking (ISSUE 10 tentpole b)."""

    def test_lazy_growth_across_block_boundaries(self, net, prompts):
        """One slot, 13 generated tokens (3 + 13 = 16 = 4 blocks):
        the table must track the write frontier exactly — after every
        step, owned blocks == blocks_needed(pos) — and the lazily-grown
        stream must stay bit-equal to whole-batch generate()."""
        ref = generate(net, prompts[:1], 13, temperature=0)[0]
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=8, block_len=BL)
        (slot, first, done), = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=13)])
        assert not done
        assert len(eng.slots[slot].blocks) == 1      # prompt (3) only
        out, guard = [first], 0
        while eng.active.any():
            emitted, _ = eng.step()
            out.extend(emitted.get(slot, []))
            if eng.slots[slot] is not None:
                assert len(eng.slots[slot].blocks) == blocks_needed(
                    int(eng.pos[slot]), BL)
            guard += 1
            assert guard < 40
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert eng.block_grants_total == 4           # 1 admit + 3 lazy
        assert eng.evict_requeue_total == 0          # no pressure here

    def test_concurrency_2x_vs_upfront_same_pool(self, net, prompts):
        """The acceptance bar: at the SAME pool size, incremental
        allocation admits >= 2x the up-front-grant baseline's
        concurrent short-generation streams (each stream's budget is 4
        blocks but its prompt occupies 1)."""

        def burst(allocation):
            eng = PagedDecodeEngine(net, n_slots=4, n_blocks=9,
                                    block_len=BL, allocation=allocation)
            return len(eng.admit_many(
                [dict(prompt_ids=prompts[r], n_tokens=13)
                 for r in range(4)]))

        upfront, incremental = burst("upfront"), burst("incremental")
        assert upfront == 2                  # 8 usable // 4-block grants
        assert incremental == 4              # prompt footprint only
        assert incremental >= 2 * upfront

    def test_pool_pressure_preempts_lowest_progress(self, net, prompts):
        """Growth under a full pool must evict the slot whose request
        emitted the FEWEST tokens (requeue costs it the least re-prefill
        work), hand it to drain_preempted(), and let the survivor
        finish exactly."""
        ref_a = generate(net, prompts[:1], 13, temperature=0)[0]
        ref_b = generate(net, prompts[1:2], 6, temperature=0)[0]
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=5,
                                block_len=BL)   # 4 usable
        (sa, fa, _), = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=13, request_id="A")])
        out_a = [fa]
        for _ in range(3):                   # A builds a progress lead
            emitted, _ = eng.step()
            out_a.extend(emitted.get(sa, []))
        (sb, fb, _), = eng.admit_many(
            [dict(prompt_ids=prompts[1], n_tokens=6, request_id="B")])
        out_b = [fb]
        guard = 0
        while eng.active.any():
            emitted, _ = eng.step()
            out_a.extend(emitted.get(sa, []))
            out_b.extend(emitted.get(sb, []))
            guard += 1
            assert guard < 40
        # A (emitted 5+) and B (emitted 2) both needed growth with the
        # pool exhausted: the LOWEST-progress slot must be the victim
        notes = eng.drain_preempted()
        assert [n["request_id"] for n in notes] == ["B"], \
            "pool pressure must evict the lowest-progress slot"
        assert notes[0]["emitted"] == len(out_b)
        assert 1 <= len(out_b) < 6            # preempted mid-stream
        np.testing.assert_array_equal(np.asarray(out_a), ref_a)
        # requeue B as a continuation: original prompt + every emitted
        # token, generating the remainder — the stream must complete
        # exactly as if never interrupted
        cont = np.concatenate([prompts[1], np.asarray(out_b)])
        (sb2, f2, _), = eng.admit_many(
            [dict(prompt_ids=cont, n_tokens=6 - len(out_b),
                  request_id="B", emit_start=len(out_b))])
        out_b.append(f2)
        drain_engine(eng, {sb2: 0}, {0: out_b})
        np.testing.assert_array_equal(np.asarray(out_b), ref_b)
        assert eng.evict_requeue_total == 1

    def test_fragmented_free_list_churn(self):
        """Evict/readmit reuse across a FRAGMENTED free list: grants
        interleave with frees, all-or-nothing holds at every point, and
        the double-free guard survives the churn."""
        a = BlockAllocator(10)               # 9 usable
        s1, s2, s3 = a.allocate(3), a.allocate(3), a.allocate(3)
        a.free(s1)
        a.free(s3)                           # free list now fragmented
        assert a.free_blocks == 6
        got = a.allocate(5)                  # spans both fragments
        assert got is not None and len(set(got)) == 5
        assert set(got) <= set(s1) | set(s3)
        assert a.allocate(2) is None         # 1 left: all-or-nothing
        a.free(got[:1])
        with pytest.raises(ValueError, match="double-free"):
            a.free(got[:1])                  # churn must not erode it
        a.free(got[1:])
        a.free(s2)
        assert a.free_blocks == 9            # full pool recovered

    def test_server_requeue_completes_with_parity(self, net, prompts,
                                                  ref_tokens):
        """End-to-end: a pool too small for every stream's full length
        forces preempt-and-requeue mid-serving; every stream must still
        complete bit-equal to whole-batch generate() (continuation
        prefill reproduces the decode-path numerics)."""
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=4, n_blocks=5,
                               block_len=BL).start()   # 4 usable blocks
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(4)]
            got = np.stack([s.result(timeout=120) for s in streams])
        finally:
            srv.stop()
            monitor.disable()
        np.testing.assert_array_equal(got, ref_tokens[:4])
        assert srv.engine.evict_requeue_total >= 1, \
            "pool pressure never fired — the test pool is too large"
        assert (reg.counter("serving_evict_requeue_total").value
                == srv.engine.evict_requeue_total)
        assert (reg.counter("serving_block_grants_total").value
                == srv.engine.block_grants_total)
        expo = reg.exposition()
        assert "serving_pool_blocks_free" in expo
        assert "serving_pool_blocks_used" in expo


class TestQuantizedDecode:
    """Int8 weight-only quantization (nd/quant.py): the parity contract
    is greedy top-1 agreement over FULL generations on the zoo LM plus
    bounded logit error, and the engine must serve quantized weights
    bit-equal to `generate(quantize="int8")` (ISSUE 10 tentpole a)."""

    def test_quantize_roundtrip_and_seam_units(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nd import quant
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.standard_normal((24, 16)), jnp.float32)
        qt = quant.quantize(w)
        assert qt.q.dtype == jnp.int8 and qt.shape == w.shape
        # symmetric per-output-channel: |error| <= scale/2 everywhere
        deq = quant.dequantize(qt)
        assert np.all(np.abs(np.asarray(deq - w))
                      <= np.asarray(qt.scale) / 2 + 1e-7)
        # the matmul seam scales AFTER the contraction — numerically
        # the same product (per-channel scale commutes with the sum)
        x = jnp.asarray(rng.standard_normal((5, 24)), jnp.float32)
        np.testing.assert_allclose(np.asarray(quant.matmul(x, qt)),
                                   np.asarray(x @ deq), rtol=1e-5,
                                   atol=1e-6)
        # an all-zero output channel must quantize exactly, not NaN
        wz = w.at[:, 3].set(0.0)
        qz = quant.quantize(wz)
        assert np.all(np.asarray(qz.q)[:, 3] == 0)
        assert np.isfinite(np.asarray(qz.scale)).all()
        with pytest.raises(ValueError, match="ndim"):
            quant.quantize(jnp.zeros(7))
        with pytest.raises(ValueError, match="unknown quantization"):
            quant.quantize_net_params(tiny_lm(), "int4")

    def test_quantized_params_tree_and_bytes(self, net):
        from deeplearning4j_tpu.nd import quant
        qp = quant.serving_params(net, "int8")
        plan = quant.quantized_weight_keys(net)
        assert plan, "zoo LM declared no quantizable weights"
        for lk, pks in plan.items():
            for pk in pks:
                assert isinstance(qp[lk][pk], quant.QuantizedTensor)
                # the training master is untouched
                assert not isinstance(net.params[lk][pk],
                                      quant.QuantizedTensor)
        # one quantization pass per net per mode (admission + decode +
        # prefill all share the tree)
        assert quant.serving_params(net, "int8") is qp
        assert quant.serving_params(net, None) is net.params
        mm_fp = quant.weight_bytes(
            {lk: {pk: net.params[lk][pk] for pk in pks}
             for lk, pks in plan.items()})
        mm_q = quant.weight_bytes(
            {lk: {pk: qp[lk][pk] for pk in pks}
             for lk, pks in plan.items()})
        # int8 + per-channel fp32 scale vs fp32: ~3.9x on the matmul
        # weights themselves (the tiny d16 test net bounds it lower)
        assert mm_fp / mm_q > 3.0, (mm_fp, mm_q)
        assert (quant.weight_bytes(net.params)
                / quant.weight_bytes(qp)) > 2.5

    def test_quantized_cache_invalidates_on_fit(self):
        """serving_params caches per net — but fit() reassigns
        net.params, and the cache MUST follow: a fine-tuned net must
        never silently serve pre-training int8 weights while its fp
        path serves the fresh ones."""
        from deeplearning4j_tpu.nd import quant
        net = tiny_lm(seed=5)
        qp1 = quant.serving_params(net, "int8")
        assert quant.serving_params(net, "int8") is qp1   # cached
        rng = np.random.default_rng(0)
        X = rng.integers(0, V, (8, 4)).astype(np.float32)
        Y = np.eye(V, dtype=np.float32)[rng.integers(0, V, (8, 4))]
        net.fit(X, Y, epochs=1, batch_size=8)
        qp2 = quant.serving_params(net, "int8")
        assert qp2 is not qp1, "stale quantized cache survived fit()"
        w_new = np.asarray(quant.dequantize(qp2["0"]["W"]))
        w_old = np.asarray(quant.dequantize(qp1["0"]["W"]))
        assert not np.array_equal(w_new, w_old), \
            "refreshed quantized tree does not reflect the new weights"

    def test_engine_serves_live_params_after_fit(self):
        """The engine resolves its params tree PER DISPATCH: a fit()
        (or checkpoint restore) between engine construction and decode
        must serve the fresh weights, not a construction-time
        snapshot — in fp mode (identity with net.params) and int8 mode
        (re-quantized via the identity-keyed cache)."""
        from deeplearning4j_tpu.nd import quant
        net = tiny_lm(seed=8)
        eng = PagedDecodeEngine(net, n_slots=1, n_blocks=8,
                                block_len=BL)
        qeng = PagedDecodeEngine(net, n_slots=1, n_blocks=8,
                                 block_len=BL, quantize="int8")
        assert eng._params is net.params
        qp_before = qeng._params
        rng = np.random.default_rng(1)
        X = rng.integers(0, V, (8, 4)).astype(np.float32)
        Y = np.eye(V, dtype=np.float32)[rng.integers(0, V, (8, 4))]
        net.fit(X, Y, epochs=1, batch_size=8)
        assert eng._params is net.params, \
            "engine kept a stale fp params snapshot across fit()"
        assert qeng._params is not qp_before, \
            "engine kept stale int8 weights across fit()"
        assert quant.serving_params(net, "int8") is qeng._params

    def test_greedy_top1_agreement_trained_lm(self):
        """The parity contract on a TRAINED zoo LM (random-init logits
        are near-ties — argmax there measures noise, not the
        quantization): full-generation top-1 agreement, plus the
        bounded-probability-error clause."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nd import quant
        from deeplearning4j_tpu.nn.layers.recurrent import (
            BaseRecurrentLayer)
        from deeplearning4j_tpu.zoo.transformer import get_prefill
        net = TransformerLM(vocab_size=V, d_model=32, n_layers=2,
                            n_heads=4, max_len=24, seed=11).init()
        corpus = (np.arange(512) * 3) % V    # learnable cyclic stream
        X = np.stack([corpus[i:i + 8] for i in range(0, 500, 2)])
        Y = np.stack([corpus[i + 1:i + 9] for i in range(0, 500, 2)])
        net.fit(X.astype(np.float32), np.eye(V, dtype=np.float32)[Y],
                epochs=20, batch_size=50, shuffle=False)
        pr = np.stack([corpus[i:i + 4]
                       for i in (0, 7, 20, 33, 46, 59, 72, 85)])
        fp = generate(net, pr, 16, temperature=0)
        q8 = generate(net, pr, 16, temperature=0, quantize="int8")
        agree = float((fp == q8).mean())
        assert agree == 1.0, \
            f"greedy top-1 agreement {agree:.3f} < 1.0 over full " \
            f"generations:\nfp={fp}\nint8={q8}"
        # bounded logit error: next-token distributions of the same
        # prefill program under fp vs int8 weights (measured ~4e-4 on
        # this config; the bound leaves a 10x margin)
        prefill = get_prefill(net)

        def carries():
            return {str(i): l.init_carry(len(pr),
                                         net.dtype.compute_dtype)
                    for i, l in enumerate(net.layers)
                    if isinstance(l, BaseRecurrentLayer)}

        p_fp, _ = prefill(net.params, net.net_state, jnp.asarray(pr),
                          carries())
        p_q8, _ = prefill(quant.serving_params(net, "int8"),
                          net.net_state, jnp.asarray(pr), carries())
        err = float(jnp.abs(p_fp - p_q8).max())
        assert err < 5e-3, f"probability error {err} out of bound"

    def test_quantized_engine_bit_equal_noise_pools(self, net, prompts):
        """The engine's quantized decode must be BIT-equal to
        `generate(quantize='int8')` — through noise-filled pools and a
        non-contiguous, fragmented block table (garbage pages must
        contribute exactly 0.0)."""
        import jax.numpy as jnp
        qref = generate(net, prompts[:3], 6, temperature=0,
                        quantize="int8")
        eng = PagedDecodeEngine(net, n_slots=3, n_blocks=16,
                                block_len=BL, quantize="int8")
        key = np.random.default_rng(9)
        eng.pool.kv = tuple(
            (k + jnp.asarray(key.standard_normal(k.shape), k.dtype),
             v + jnp.asarray(key.standard_normal(v.shape), v.dtype))
            for k, v in eng.pool.kv)
        # fragment the free list so the real requests' tables are
        # non-contiguous
        decoys = eng.admit_many(
            [dict(prompt_ids=prompts[3], n_tokens=6),
             dict(prompt_ids=prompts[4], n_tokens=6)])
        for slot, _, _ in decoys:
            eng.evict(slot)
        admitted = eng.admit_many(
            [dict(prompt_ids=prompts[r], n_tokens=6) for r in range(3)])
        assert len(admitted) == 3
        out = {r: [admitted[r][1]] for r in range(3)}
        drain_engine(eng, {admitted[r][0]: r for r in range(3)}, out)
        got = np.asarray([out[r] for r in range(3)])
        np.testing.assert_array_equal(got, qref)

    def test_mixed_length_wave_admits_heterogeneous_prompts(self, net):
        """ONE admission wave with three DIFFERENT prompt lengths
        (bucket-padded into a single prefill dispatch) must admit all
        of them with streams equal to their whole-batch generate()
        rows — the same-length-wave restriction is gone (tentpole c)."""
        rng = np.random.default_rng(4)
        mixed = [rng.integers(0, V, n) for n in (2, 3, 5)]
        refs = [generate(net, p[None], 6, temperature=0)[0]
                for p in mixed]
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                block_len=BL)
        admitted = eng.admit_many(
            [dict(prompt_ids=p, n_tokens=6) for p in mixed])
        assert len(admitted) == 3
        out = {r: [admitted[r][1]] for r in range(3)}
        drain_engine(eng, {admitted[r][0]: r for r in range(3)}, out)
        for r, ref in enumerate(refs):
            np.testing.assert_array_equal(np.asarray(out[r]), ref,
                                          err_msg=f"prompt len "
                                          f"{mixed[r].shape[0]}")

    def test_server_quantized_mixed_length_parity(self, net):
        """Server-level: quantize='int8' + heterogeneous prompt lengths
        submitted concurrently — every stream bit-equal to
        generate(quantize='int8') of its own prompt."""
        rng = np.random.default_rng(6)
        mixed = [rng.integers(0, V, (3, 2, 5, 3, 2, 5)[r])
                 for r in range(6)]
        refs = [generate(net, p[None], 6, temperature=0,
                         quantize="int8")[0] for p in mixed]
        srv = GenerationServer(net, n_slots=4, n_blocks=16,
                               block_len=BL, quantize="int8").start()
        try:
            streams = [srv.generate_async(p, 6) for p in mixed]
            got = [s.result(timeout=120) for s in streams]
        finally:
            srv.stop()
        for r, ref in enumerate(refs):
            np.testing.assert_array_equal(got[r], ref)


class TestSampledDeterminism:
    def test_same_stream_alone_or_batched(self, net, prompts):
        """The serving rng contract: token t of a request derives from
        fold_in(request_key, t) — the stream must not depend on what
        else is in flight (whole-batch generate() cannot offer this;
        the serving tier guarantees it)."""
        key = np.asarray([7, 9], np.uint32)

        def run(extra):
            eng = PagedDecodeEngine(net, n_slots=4, n_blocks=24,
                                    block_len=BL)
            reqs = [dict(prompt_ids=prompts[0], n_tokens=6,
                         temperature=1.0, top_p=0.9, rng=key)]
            for e in range(extra):
                reqs.append(dict(prompt_ids=prompts[e + 1], n_tokens=6,
                                 temperature=0.7,
                                 rng=np.asarray([e, e], np.uint32)))
            admitted = eng.admit_many(reqs)
            out = {r: [admitted[r][1]] for r in range(len(reqs))}
            drain_engine(
                eng, {admitted[r][0]: r for r in range(len(reqs))}, out)
            return out[0]

        alone = run(0)
        batched = run(3)
        assert alone == batched
        assert all(0 <= t < V for t in alone)

    def test_greedy_and_sampled_mix_keeps_greedy_exact(self, net,
                                                       prompts,
                                                       ref_tokens):
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=16,
                                block_len=BL)
        admitted = eng.admit_many([
            dict(prompt_ids=prompts[0], n_tokens=6),    # greedy
            dict(prompt_ids=prompts[1], n_tokens=6, temperature=1.0,
                 rng=np.asarray([1, 2], np.uint32)),
        ])
        out = {r: [admitted[r][1]] for r in range(2)}
        drain_engine(eng, {admitted[r][0]: r for r in range(2)}, out)
        np.testing.assert_array_equal(np.asarray(out[0]), ref_tokens[0])


class TestGenerationServer:
    def test_concurrent_streams_greedy_parity(self, net, prompts,
                                              ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(6)]
            got = np.stack([s.result(timeout=120) for s in streams])
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens)

    def test_iterator_streams_tokens_incrementally(self, net, prompts,
                                                   ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            toks = list(srv.generate_async(prompts[0], 6))
        finally:
            srv.stop()
        assert toks == list(ref_tokens[0])

    def test_pool_exhaustion_queues_not_corrupts(self, net, prompts,
                                                 ref_tokens):
        """Pool holds ONE sequence: 4 concurrent requests must all
        complete exactly (later ones wait for blocks; nothing reads
        another sequence's pages)."""
        srv = GenerationServer(net, n_slots=4, n_blocks=4,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(4)]
            got = np.stack([s.result(timeout=120) for s in streams])
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens[:4])

    def test_cancel_midstream_and_while_queued(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=5,
                               block_len=BL,
                               steps_per_dispatch=1).start()
        try:
            # A holds the only slot; B is necessarily still queued
            # (pool fits ONE sequence) — cancelling B must retire it
            # without it ever touching a slot
            a = srv.generate_async(prompts[0], 12)
            b = srv.generate_async(prompts[1], 12)
            it = iter(a)
            first = next(it)
            b.cancel()
            a.cancel()                       # mid-stream (best effort)
            got = [first] + list(it)
            assert 1 <= len(got) <= 12
            assert list(a.result(timeout=30)) == got
            assert list(b.result(timeout=30)) == []
            # slot + blocks are free again: a new request runs fully
            s2 = srv.generate_async(prompts[2], 6)
            assert len(s2.result(timeout=120)) == 6
        finally:
            srv.stop()

    def test_shed_under_overload(self, net, prompts):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=1, n_blocks=4,
                               block_len=BL, max_queue=1,
                               slo_ttft_s=1e-3).start()
        try:
            streams = [srv.generate_async(prompts[r % 6], 6)
                       for r in range(8)]
            shed = ok = 0
            for s in streams:
                try:
                    s.result(timeout=120)
                    ok += 1
                except ShedError:
                    shed += 1
        finally:
            srv.stop()
            monitor.disable()
        assert shed >= 1 and ok >= 1
        assert reg.counter("serving_shed_total").value == shed

    def test_serving_metrics_families(self, net, prompts):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[r], 6)
                       for r in range(3)]
            for s in streams:
                s.result(timeout=120)
            deadline = time.monotonic() + 5
            while (reg.timer("serving_tpot_seconds").count < 3
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            srv.stop()
            monitor.disable()
        assert reg.counter("serving_requests_total").value == 3
        assert reg.counter("serving_tokens_total").value == 18
        assert reg.timer("serving_ttft_seconds").count == 3
        assert reg.timer("serving_tpot_seconds").count == 3
        assert reg.counter("serving_shed_total").value == 0
        exposition = reg.exposition()
        for fam in ("serving_queue_depth", "serving_active_slots",
                    "serving_free_blocks", "serving_ttft_seconds"):
            assert fam in exposition

    def test_stop_fails_inflight_and_queued(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=4,
                               block_len=BL).start()
        streams = [srv.generate_async(prompts[r % 6], 6)
                   for r in range(4)]
        srv.stop()
        outcomes = []
        for s in streams:
            try:
                s.result(timeout=10)
                outcomes.append("ok")
            except RuntimeError:
                outcomes.append("failed")
        # nothing may HANG; at least the queued tail must have failed
        assert len(outcomes) == 4 and "failed" in outcomes

    def test_validation_eager(self, net, prompts):
        srv = GenerationServer(net, n_slots=1, n_blocks=8, block_len=BL)
        with pytest.raises(RuntimeError, match="start"):
            srv.generate_async(prompts[0], 6)
        srv.start()
        try:
            with pytest.raises(ValueError, match="page budget"):
                srv.generate_async(prompts[0], MAXLEN + 1)
            # within the page budget but needing more blocks than the
            # whole pool owns: must fail at submit, not deadlock queued
            small = GenerationServer(net, n_slots=1, n_blocks=3,
                                     block_len=BL)
            with pytest.raises(ValueError, match="never be admitted"):
                small.engine.check_budget(3, 12)   # 4 blocks > 2 usable
            with pytest.raises(ValueError, match="top_p"):
                srv.generate_async(prompts[0], 4, top_p=0.0)
            with pytest.raises(ValueError, match="non-empty"):
                srv.generate_async(np.zeros((0,), np.int32), 4)
        finally:
            srv.stop()

    def test_warmup_compiles_before_start(self, net, prompts,
                                          ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL)
        srv.warmup(prompts.shape[1], 6)
        # the warmup grid's synthetic grants/preemptions must not leak
        # into the serving-traffic counters (registry deltas + ledger)
        assert srv.engine.block_grants_total == 0
        assert srv.engine.evict_requeue_total == 0
        srv.start()
        try:
            got = srv.generate_async(prompts[0], 6).result(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(got, ref_tokens[0])
        assert srv.engine.block_grants_total > 0
        with pytest.raises(RuntimeError, match="before start"):
            GenerationServer(net, n_slots=2, n_blocks=16,
                             block_len=BL).start().warmup(3)

    def test_warmup_covers_budget_clamped_top_bucket(self, net):
        """A prompt that buckets to the FULL stream budget leaves no
        token headroom at that bucket — warmup must still compile the
        (width, budget-bucket) prefill programs (with a one-shorter
        prompt that pads to the same bucket), or the first budget-edge
        request stalls live streams on a trace."""
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL)
        srv.warmup(MAXLEN - 2, 2).start()   # top bucket == MAXLEN
        try:
            got = srv.generate_async(
                np.zeros(MAXLEN - 2, np.int32), 2).result(timeout=120)
            assert len(got) == 2
        finally:
            srv.stop()


class TestServingBenchGate:
    def test_compare_bench_gates_serving_metrics(self):
        from deeplearning4j_tpu.bench import compare_bench

        def rec(tps, speedup):
            return {"platform": "cpu-sandbox", "value": 100.0,
                    "extras": {"serving": {
                        "tokens_per_sec": tps,
                        "speedup_vs_sequential": speedup}}}

        base = rec(5000.0, 1.5)
        assert compare_bench(rec(4900.0, 1.45), base)["status"] == "pass"
        verdict = compare_bench(rec(2000.0, 1.5), base)
        assert verdict["status"] == "regression"
        assert any(r["metric"] == "serving_tokens_per_sec"
                   for r in verdict["regressions"])
        verdict = compare_bench(rec(5000.0, 0.9), base)
        assert verdict["status"] == "regression"
        assert any(r["metric"] == "serving_speedup_vs_sequential"
                   for r in verdict["regressions"])

    def test_compare_bench_gates_quantized_serving(self):
        from deeplearning4j_tpu.bench import compare_bench

        def rec(tps, reduction, ttft):
            return {"platform": "cpu-sandbox", "value": 100.0,
                    "extras": {"serving_mixed_quantized": {
                        "tokens_per_sec": tps,
                        "weight_bytes_reduction": reduction,
                        "p50_ttft_ms": ttft}}}

        base = rec(8000.0, 3.6, 40.0)
        assert compare_bench(rec(7800.0, 3.62, 42.0),
                             base)["status"] == "pass"
        # quantized throughput collapse gates
        v = compare_bench(rec(3000.0, 3.6, 40.0), base)
        assert v["status"] == "regression"
        assert any(r["metric"] == "serving_quantized_tokens_per_sec"
                   for r in v["regressions"])
        # STALE-FALLBACK detection: a run that silently served fp
        # weights reports ~1.0x against the int8 baseline's ~3.6x —
        # the structural 2% band catches it even if throughput held
        v = compare_bench(rec(8000.0, 1.0, 40.0), base)
        assert v["status"] == "regression"
        assert any(
            r["metric"] == "serving_quantized_weight_bytes_reduction"
            for r in v["regressions"])
        # TTFT is lower-is-better: a RISE past tolerance gates...
        v = compare_bench(rec(8000.0, 3.6, 100.0), base)
        assert v["status"] == "regression"
        assert any(r["metric"] == "serving_mixed_p50_ttft_ms"
                   for r in v["regressions"])
        # ...while a big DROP (improvement) passes
        assert compare_bench(rec(8000.0, 3.6, 10.0),
                             base)["status"] == "pass"


class TestServingUI:
    def test_serving_page_renders_registry_state(self, net, prompts):
        import urllib.request

        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui import UIServer

        reg = monitor.enable(registry=MetricsRegistry())
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            srv.generate_async(prompts[0], 6).result(timeout=120)
        finally:
            srv.stop()
            monitor.disable()
        ui = UIServer(registry=reg).start()
        try:
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(base + "/serving",
                                          timeout=10).read().decode()
            assert "requests admitted" in html
            assert "free pool blocks" in html
            assert "pool occupancy" in html
            assert "blocks granted" in html
            mtext = urllib.request.urlopen(base + "/metrics",
                                           timeout=10).read().decode()
            assert "serving_ttft_seconds" in mtext
        finally:
            ui.stop()


class TestReviewHardening:
    def test_midwave_failure_returns_allocated_blocks(self, net,
                                                      prompts):
        """A wave interrupted AFTER earlier requests' blocks were
        allocated (here: a later request failing validation) must
        return them to the pool — no Slot owns them yet, so nothing
        else ever could (the capacity-leak -> silent-starvation
        failure)."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=16,
                                block_len=BL)
        before = eng.free_blocks
        with pytest.raises(ValueError, match="non-empty"):
            eng.admit_many([
                dict(prompt_ids=prompts[0], n_tokens=6),
                dict(prompt_ids=np.zeros((0,), np.int32), n_tokens=6),
            ])
        assert eng.free_blocks == before, "mid-wave failure leaked blocks"
        # pool still fully serviceable
        admitted = eng.admit_many(
            [dict(prompt_ids=prompts[0], n_tokens=6)])
        assert len(admitted) == 1

    def test_output_async_refused_on_generation_server(self, net):
        srv = GenerationServer(net, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        try:
            with pytest.raises(NotImplementedError, match="generate_async"):
                srv.output_async(np.zeros((1, 3), np.float32))
        finally:
            srv.stop()

    def test_warmup_covers_sampled_decode_program(self, net):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL)
        srv.warmup(3)
        assert srv.engine._decode_greedy is not None
        assert srv.engine._decode_full is not None, (
            "warmup left the sampled decode program uncompiled — the "
            "first temperature>0 request would stall live streams")

    def test_default_sampled_requests_draw_distinct_streams(self, net,
                                                            prompts):
        """rng=None + temperature>0 must NOT collapse onto the
        engine's deterministic zero key: two concurrent no-rng sampled
        requests for the SAME prompt get distinct streams (pass rng
        explicitly for reproducibility)."""
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            a = srv.generate_async(prompts[0], 8, temperature=1.0)
            b = srv.generate_async(prompts[0], 8, temperature=1.0)
            ta = list(a.result(timeout=120))
            tb = list(b.result(timeout=120))
        finally:
            srv.stop()
        assert ta != tb, "no-rng sampled requests shared one key"

    def test_cancelled_queued_requests_do_not_shed_fresh_ones(self, net,
                                                              prompts):
        """Cancelled entries stranded mid-queue must stop counting
        toward max_queue / the shed projection — phantom load must not
        shed real requests."""
        srv = GenerationServer(net, n_slots=1, n_blocks=5,
                               block_len=BL, max_queue=2,
                               steps_per_dispatch=1).start()
        try:
            a = srv.generate_async(prompts[0], 12)   # holds the slot
            queued = [srv.generate_async(prompts[1], 6)
                      for _ in range(2)]             # fills max_queue
            for s in queued:
                s.cancel()
            # give the scheduler a beat to reap the cancelled entries
            for s in queued:
                s.result(timeout=30)
            fresh = srv.generate_async(prompts[2], 6)
            got = fresh.result(timeout=120)          # must NOT ShedError
            assert len(got) == 6
            a.result(timeout=120)
        finally:
            srv.stop()
