"""Request-plane lifecycle tracing on the serving scheduler.

Contracts (ISSUE: observability tentpole):

- every traced request carries queued → prefill → decode phases with
  monotonic host timestamps, flushed to the Tracer on its own track;
- tracing adds ZERO device syncs (`block_until_ready` count identical
  traced vs untraced) and leaves emitted tokens bit-identical;
- shed decisions are annotated into the trace and spend SLO budget;
- `GenerationServer(name=)` labels serving_* metrics with `server=`.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import (
    MetricsRegistry,
    SLOObjective,
    Tracer,
)
from deeplearning4j_tpu.monitor.flightrec import GLOBAL_FLIGHT_RECORDER
from deeplearning4j_tpu.monitor.reqtrace import _tid_for
from deeplearning4j_tpu.serving import GenerationServer, ShedError
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 32
BL = 4


@pytest.fixture(scope="module")
def net():
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=3).init()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 3))


@pytest.fixture(scope="module")
def ref_tokens(net, prompts):
    return generate(net, prompts, 6, temperature=0)


@pytest.fixture
def mon():
    reg, tr = MetricsRegistry(), Tracer()
    monitor.enable(registry=reg, tracer=tr)
    yield reg, tr
    monitor.disable()
    monitor._STATE.registry = monitor.GLOBAL_REGISTRY
    monitor._STATE.tracer = monitor.GLOBAL_TRACER


def _serve(srv, prompts, n=6, n_tokens=6):
    streams = [srv.generate_async(prompts[r % len(prompts)], n_tokens)
               for r in range(n)]
    toks = np.stack([s.result(timeout=300) for s in streams])
    return streams, toks


class TestRequestLifecycleTrace:
    def test_phases_ordered_and_monotonic(self, mon, net, prompts,
                                          ref_tokens):
        _, tracer = mon
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams, toks = _serve(srv, prompts)
        finally:
            srv.stop()
        np.testing.assert_array_equal(toks, ref_tokens)
        ids = set()
        for s in streams:
            tr = s.trace
            assert tr is not None and tr.finished and tr.status == "ok"
            ids.add(tr.trace_id)
            names = [p["name"] for p in tr.phases]
            assert names[0] == "queued" and names[1] == "prefill"
            assert "decode" in names[2:]
            last = tr.t_created
            for p in tr.phases:
                assert p["t0"] <= p["t1"], p
                assert p["t0"] >= last - 1e-9
                last = p["t0"]
            assert tr.t_finished >= tr.phases[-1]["t1"] - 1e-9
            assert tr.meta["prompt_len"] == 3
            assert tr.meta["ttft_s"] is not None
            decode_tok = sum(p["args"]["tokens"] for p in tr.phases
                             if p["name"] == "decode")
            prefill_tok = sum(1 for p in tr.phases
                              if p["name"] == "prefill")
            assert decode_tok + prefill_tok == 6
        assert len(ids) == 6                    # one trace per request
        # each trace flushed onto its OWN tracer track
        tids = {e["tid"] for e in tracer._events
                if str(e.get("name", "")).startswith("req/lifetime")}
        assert tids == {_tid_for(i) for i in ids}

    def test_spec_counts_attributed_per_dispatch(self, mon, net):
        """Single slot: every dispatch's speculative delta lands on
        exactly one decode phase, so the per-trace sum equals the
        engine counter."""
        prompt = np.asarray([1, 2, 3, 1, 2, 3], np.int64)
        srv = GenerationServer(net, n_slots=1, n_blocks=16,
                               block_len=BL, speculative=4).start()
        try:
            s = srv.generate_async(prompt, 20)
            s.result(timeout=300)
            proposed = srv.engine.spec_proposed_total
            accepted = srv.engine.spec_accepted_total
        finally:
            srv.stop()
        decode = [p for p in s.trace.phases if p["name"] == "decode"]
        assert sum(p["args"].get("spec_proposed", 0)
                   for p in decode) == proposed
        assert sum(p["args"].get("spec_accepted", 0)
                   for p in decode) == accepted

    def test_trace_off_serving_identical_and_traceless(self, net,
                                                       prompts,
                                                       ref_tokens):
        assert not monitor.is_enabled()
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams, toks = _serve(srv, prompts)
        finally:
            srv.stop()
        np.testing.assert_array_equal(toks, ref_tokens)
        assert all(s.trace is None for s in streams)


class TestTraceOverheadContract:
    """Tracing must stamp host clocks only — the traced run performs
    exactly the device syncs the untraced run does."""

    @pytest.fixture
    def sync_counter(self, monkeypatch):
        calls = {"n": 0}
        real = jax.block_until_ready

        def counting(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(jax, "block_until_ready", counting)
        return calls

    def test_traced_equals_untraced_syncs(self, sync_counter, net,
                                          prompts, ref_tokens):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            _, toks_off = _serve(srv, prompts)
        finally:
            srv.stop()
        untraced = sync_counter["n"]
        monitor.enable(registry=MetricsRegistry(), tracer=Tracer())
        try:
            srv = GenerationServer(net, n_slots=2, n_blocks=16,
                                   block_len=BL).start()
            try:
                _, toks_on = _serve(srv, prompts)
            finally:
                srv.stop()
        finally:
            monitor.disable()
            monitor._STATE.registry = monitor.GLOBAL_REGISTRY
            monitor._STATE.tracer = monitor.GLOBAL_TRACER
        assert sync_counter["n"] - untraced == untraced or untraced == 0
        assert sync_counter["n"] == 2 * untraced
        np.testing.assert_array_equal(toks_on, toks_off)
        np.testing.assert_array_equal(toks_on, ref_tokens)


class TestShedTraceAndSLO:
    def test_shed_annotated_and_spends_budget(self, mon, net, prompts):
        reg, _ = mon
        before = len(GLOBAL_FLIGHT_RECORDER.events(kind="shed_burst"))
        srv = GenerationServer(net, n_slots=1, n_blocks=4,
                               block_len=BL, max_queue=1,
                               slo_ttft_s=1e-3, name="shedder",
                               slo=SLOObjective(ttft_s=60.0)).start()
        try:
            streams = [srv.generate_async(prompts[r % 6], 6)
                       for r in range(8)]
            shed = ok = 0
            for s in streams:
                try:
                    s.result(timeout=300)
                    ok += 1
                except ShedError:
                    shed += 1
                    tr = s.trace
                    assert tr is not None and tr.status == "shed"
                    ev = [e for e in tr.events if e["name"] == "shed"]
                    assert ev and ev[0]["args"]["reason"]
        finally:
            srv.stop()
        assert shed >= 1 and ok >= 1
        snap = reg.snapshot()
        good = snap["slo_requests_good_total"]["values"][0]
        bad = snap["slo_requests_bad_total"]["values"][0]
        assert good["labels"] == {"model": "shedder"}
        assert good["value"] == ok and bad["value"] == shed
        burn = snap["slo_burn_rate"]["values"][0]["value"]
        assert burn > 0.0                       # sheds burned budget
        assert len(GLOBAL_FLIGHT_RECORDER.events(kind="shed_burst")) \
            > before

    def test_slo_all_good_when_target_generous(self, mon, net, prompts,
                                               ref_tokens):
        reg, _ = mon
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL, name="roomy",
                               slo=SLOObjective(ttft_s=600.0,
                                                tpot_s=600.0)).start()
        try:
            streams, toks = _serve(srv, prompts)
        finally:
            srv.stop()
        np.testing.assert_array_equal(toks, ref_tokens)
        snap = reg.snapshot()
        assert snap["slo_requests_good_total"]["values"][0]["value"] == 6
        assert "slo_requests_bad_total" in snap
        assert snap["slo_requests_bad_total"]["values"][0]["value"] == 0
        assert all(s.trace.meta["slo_good"] for s in streams)


class TestServerNameLabel:
    def test_named_server_labels_families(self, mon, net, prompts):
        reg, _ = mon
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL, name="alpha").start()
        try:
            srv.generate_async(prompts[0], 6).result(timeout=300)
        finally:
            srv.stop()
        fam = reg.snapshot()["serving_requests_total"]
        assert fam["values"][0]["labels"] == {"server": "alpha"}
        text = reg.exposition()
        assert 'serving_requests_total{server="alpha"} 1' in text

    def test_unnamed_server_stays_unlabeled(self, mon, net, prompts):
        reg, _ = mon
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            srv.generate_async(prompts[0], 6).result(timeout=300)
        finally:
            srv.stop()
        fam = reg.snapshot()["serving_requests_total"]
        assert fam["values"][0].get("labels", {}) == {}
