"""Tests for the round-1 layer additions: VAE, RBM, FrozenLayer,
CenterLoss, YOLOv2, dropout family, weight noise, constraints,
Upsampling1D.

Mirrors the reference test strategy (SURVEY.md §4): tiny real networks,
numeric assertions, gradient checks where the math is deterministic
(`VaeGradientCheckTests`, `YoloGradientCheckTests` analogues).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam, Sgd
from deeplearning4j_tpu.gradientcheck import check_gradients_fn
from deeplearning4j_tpu.nn.conf import (
    AlphaDropout,
    Dropout,
    DropConnect,
    GaussianDropout,
    GaussianNoise,
    InputType,
    MaxNormConstraint,
    MinMaxNormConstraint,
    NeuralNetConfiguration,
    NonNegativeConstraint,
    UnitNormConstraint,
    WeightNoise,
)
from deeplearning4j_tpu.nn.layers import (
    RBM,
    CenterLossOutputLayer,
    DenseLayer,
    FrozenLayer,
    GaussianReconstructionDistribution,
    BernoulliReconstructionDistribution,
    OutputLayer,
    Upsampling1D,
    VariationalAutoencoder,
    Yolo2OutputLayer,
)
from deeplearning4j_tpu.nn.layers.base import layer_from_dict
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.datasets.dataset import DataSet

KEY = jax.random.PRNGKey(0)


def _mlp_conf(out_layer, n_in=8, hidden=12, **kw):
    b = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2)).list()
         .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh", **kw))
         .layer(out_layer))
    return b.set_input_type(InputType.feed_forward(n_in)).build()


# --------------------------------------------------------------------- VAE
class TestVAE:
    def _vae(self, recon=None):
        return VariationalAutoencoder(
            n_in=6, n_out=3, encoder_layer_sizes=(8,), decoder_layer_sizes=(8,),
            reconstruction_distribution=recon, activation="tanh")

    def test_param_names_match_reference(self):
        vae = self._vae()
        params = vae.init_params(KEY)
        assert set(params) == {"e0W", "e0b", "pZXMeanW", "pZXMeanb",
                               "pZXLogStd2W", "pZXLogStd2b", "d0W", "d0b",
                               "pXZW", "pXZb"}
        # gaussian recon → 2*n_in dist params
        assert params["pXZW"].shape == (8, 12)

    def test_forward_is_latent_mean(self):
        vae = self._vae()
        params = vae.init_params(KEY)
        x = jax.random.normal(KEY, (5, 6))
        y, _ = vae.forward(params, {}, x)
        assert y.shape == (5, 3)

    def test_elbo_gradcheck(self):
        # VaeGradientCheckTests analogue: deterministic given fixed rng
        vae = self._vae()
        params = vae.init_params(KEY)
        x = np.random.default_rng(0).standard_normal((4, 6))
        rng = jax.random.PRNGKey(7)
        ok, worst, fails = check_gradients_fn(
            lambda p: vae.pretrain_loss(p, jnp.asarray(x), rng), params,
            max_params_per_array=16, max_rel_error=1e-4)
        assert ok, f"worst rel err {worst}: {fails[:3]}"

    def test_pretrain_reduces_loss(self):
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-3)).list()
                .layer(self._vae())
                .layer(OutputLayer(n_in=3, n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(1).standard_normal((64, 6)).astype(np.float32)
        vae = net.layers[0]
        l0 = float(vae.pretrain_loss(net.params["0"], jnp.asarray(x),
                                     jax.random.PRNGKey(0)))
        net.pretrain(x, epochs=30, batch_size=64)
        l1 = float(vae.pretrain_loss(net.params["0"], jnp.asarray(x),
                                     jax.random.PRNGKey(0)))
        assert l1 < l0

    def test_bernoulli_recon_and_serde(self):
        vae = self._vae(recon=BernoulliReconstructionDistribution())
        params = vae.init_params(KEY)
        assert params["pXZW"].shape == (8, 6)
        clone = layer_from_dict(vae.to_dict())
        assert clone == vae
        assert isinstance(clone.reconstruction_distribution,
                          BernoulliReconstructionDistribution)

    def test_reconstruction_probability(self):
        vae = self._vae()
        params = vae.init_params(KEY)
        x = jax.random.normal(KEY, (3, 6))
        lp = vae.reconstruction_probability(params, x, jax.random.PRNGKey(3),
                                            num_samples=4)
        assert lp.shape == (3,)
        assert np.all(np.isfinite(np.asarray(lp)))


# --------------------------------------------------------------------- RBM
class TestRBM:
    def test_cd1_learns_data(self):
        rbm = RBM(n_in=6, n_out=10, k=1)
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1)).list()
                .layer(rbm).layer(OutputLayer(n_in=10, n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        # two binary prototype patterns
        rng = np.random.default_rng(0)
        protos = np.array([[1, 1, 1, 0, 0, 0], [0, 0, 0, 1, 1, 1]], np.float32)
        x = protos[rng.integers(0, 2, 128)]
        fe0 = float(np.mean(np.asarray(rbm.free_energy(net.params["0"],
                                                       jnp.asarray(x)))))
        net.pretrain(x, epochs=20, batch_size=128)
        fe1 = float(np.mean(np.asarray(rbm.free_energy(net.params["0"],
                                                       jnp.asarray(x)))))
        assert fe1 < fe0  # data free energy falls as the model learns it

    def test_param_names(self):
        params = RBM(n_in=4, n_out=3).init_params(KEY)
        assert set(params) == {"W", "b", "vb"}

    def test_serde(self):
        rbm = RBM(n_in=4, n_out=3, hidden_unit="gaussian", k=2)
        assert layer_from_dict(rbm.to_dict()) == rbm


# ------------------------------------------------------------- FrozenLayer
class TestFrozenLayer:
    def test_frozen_params_do_not_change(self):
        inner = DenseLayer(n_in=8, n_out=12, activation="tanh")
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2)).list()
                .layer(FrozenLayer(layer=inner))
                .layer(OutputLayer(n_in=12, n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        w_before = np.asarray(net.params["0"]["W"]).copy()
        rng = np.random.default_rng(2)
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.fit(x, y, epochs=5, batch_size=32)
        np.testing.assert_array_equal(w_before, np.asarray(net.params["0"]["W"]))
        # the unfrozen head did train
        assert float(net.score(DataSet(x, y))) < 1.2

    def test_serde_roundtrip(self):
        fl = FrozenLayer(layer=DenseLayer(n_in=4, n_out=5))
        clone = layer_from_dict(fl.to_dict())
        assert isinstance(clone.layer, DenseLayer)
        assert clone.layer.n_out == 5


# -------------------------------------------------------------- CenterLoss
class TestCenterLoss:
    def test_trains_and_moves_centers(self):
        out = CenterLossOutputLayer(n_in=12, n_out=3, alpha=0.5, lambda_=0.1)
        conf = _mlp_conf(out)
        net = MultiLayerNetwork(conf).init()
        assert net.params["1"]["cL"].shape == (3, 12)
        rng = np.random.default_rng(4)
        x = rng.standard_normal((48, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]
        s0 = float(net.score(DataSet(x, y)))
        net.fit(x, y, epochs=20, batch_size=48)
        assert float(net.score(DataSet(x, y))) < s0
        assert float(np.abs(np.asarray(net.params["1"]["cL"])).sum()) > 0

    def test_gradcheck(self):
        out = CenterLossOutputLayer(n_in=5, n_out=3, alpha=0.3, lambda_=0.05)
        params = out.init_params(KEY)
        params["cL"] = jax.random.normal(KEY, (3, 5)) * 0.1
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 5))
        y = np.eye(3)[rng.integers(0, 3, 4)]
        ok, worst, fails = check_gradients_fn(
            lambda p: out.compute_loss(p, {}, jnp.asarray(x), jnp.asarray(y),
                                       train=False),
            params, max_rel_error=1e-4)
        assert ok, f"worst {worst}: {fails[:3]}"


# ------------------------------------------------------------------- YOLO2
class TestYolo2:
    A = ((1.0, 1.0), (2.5, 1.5))
    C = 4

    def _make(self):
        return Yolo2OutputLayer(anchors=self.A)

    def _data(self, b=2, h=4, w=4):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((b, h, w, len(self.A) * (5 + self.C))) * 0.1
        labels = np.zeros((b, h, w, 4 + self.C), np.float32)
        # one object per image at cell (1,2), box in grid units
        for i in range(b):
            labels[i, 1, 2, 0:4] = [2.1, 1.2, 2.9, 1.8]  # x1,y1,x2,y2
            labels[i, 1, 2, 4 + (i % self.C)] = 1.0
        return jnp.asarray(x), jnp.asarray(labels)

    def test_loss_finite_and_gradcheck(self):
        yolo = self._make()
        x, labels = self._data()
        loss = yolo.compute_loss({}, {}, x, labels)
        assert np.isfinite(float(loss))
        # grad wrt input activations (layer has no params)
        g = jax.grad(lambda xx: yolo.compute_loss({}, {}, xx, labels))(x)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_forward_activation_ranges(self):
        yolo = self._make()
        x, _ = self._data()
        y, _ = yolo.forward({}, {}, x)
        b, h, w, _ = x.shape
        y = y.reshape(b, h, w, len(self.A), 5 + self.C)
        conf = np.asarray(y[..., 4])
        cls = np.asarray(y[..., 5:])
        assert conf.min() >= 0 and conf.max() <= 1
        np.testing.assert_allclose(cls.sum(-1), 1.0, rtol=1e-5)

    def test_training_reduces_loss(self):
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer
        yolo = self._make()
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(1e-3)).list()
                .layer(ConvolutionLayer(n_out=len(self.A) * (5 + self.C),
                                        kernel_size=(1, 1), activation="identity"))
                .layer(yolo)
                .set_input_type(InputType.convolutional(4, 4, 3)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 4, 3)).astype(np.float32)
        _, labels = self._data()
        s0 = float(net.score(DataSet(x, np.asarray(labels))))
        net.fit(x, np.asarray(labels), epochs=30, batch_size=2)
        assert float(net.score(DataSet(x, np.asarray(labels)))) < s0

    def test_serde(self):
        yolo = self._make()
        clone = layer_from_dict(yolo.to_dict())
        assert clone.anchors == yolo.anchors

    def _golden_logits(self, b=2, h=4, w=4):
        """Raw activations engineered so exactly two cells cross a 0.5
        confidence threshold, with known decoded boxes (golden fixture,
        mirroring `Yolo2OutputLayer.java:610-670` semantics)."""
        a, c = len(self.A), self.C
        x = np.full((b, h, w, a * (5 + c)), -6.0, np.float32)  # conf≈0.0025
        per = 5 + c
        # example 0, cell (y=1, x=2), anchor 1: conf≈0.88
        cell = x[0, 1, 2]
        cell[1 * per + 0] = 0.0      # tx → sigmoid=0.5 → cx = 2.5
        cell[1 * per + 1] = 0.0      # ty → cy = 1.5
        cell[1 * per + 2] = 0.0      # tw → w = anchor_w * e^0 = 2.5
        cell[1 * per + 3] = 0.0      # th → h = 1.5
        cell[1 * per + 4] = 2.0      # conf = sigmoid(2) ≈ 0.8808
        cell[1 * per + 5 + 3] = 4.0  # class 3 dominates the softmax
        # example 1, cell (y=3, x=0), anchor 0: conf≈0.73
        cell = x[1, 3, 0]
        cell[0 * per + 0] = 2.0      # cx = 0 + sigmoid(2) ≈ 0.8808
        cell[0 * per + 1] = -2.0     # cy = 3 + sigmoid(-2) ≈ 3.1192
        cell[0 * per + 2] = np.log(2.0)   # w = 1.0 * 2 = 2.0
        cell[0 * per + 3] = np.log(0.5)   # h = 1.0 * 0.5 = 0.5
        cell[0 * per + 4] = 1.0      # conf ≈ 0.7311
        cell[0 * per + 5 + 1] = 4.0  # class 1
        return jnp.asarray(x)

    def test_get_predicted_objects_golden(self):
        yolo = self._make()
        out, _ = yolo.forward({}, {}, self._golden_logits())
        dets = yolo.get_predicted_objects(out, 0.5)
        assert len(dets) == 2
        dets.sort(key=lambda d: d.example_number)
        d0, d1 = dets
        assert d0.example_number == 0 and d1.example_number == 1
        sig2 = 1 / (1 + np.exp(-2.0))
        np.testing.assert_allclose(
            [d0.center_x, d0.center_y, d0.width, d0.height],
            [2.5, 1.5, 2.5, 1.5], atol=1e-5)
        np.testing.assert_allclose(d0.confidence, sig2, atol=1e-5)
        assert d0.predicted_class == 3
        np.testing.assert_allclose(
            [d1.center_x, d1.center_y, d1.width, d1.height],
            [sig2, 3 + (1 - sig2), 2.0, 0.5], atol=1e-5)
        assert d1.predicted_class == 1
        # accessor parity with DetectedObject.java getTopLeftXY/BottomRight
        np.testing.assert_allclose(d0.top_left_xy, (2.5 - 1.25, 1.5 - 0.75))
        np.testing.assert_allclose(d0.bottom_right_xy, (3.75, 2.25))
        np.testing.assert_allclose(np.sum(d0.class_predictions), 1.0,
                                   rtol=1e-5)

    def test_get_predicted_objects_threshold_and_validation(self):
        import pytest
        yolo = self._make()
        out, _ = yolo.forward({}, {}, self._golden_logits())
        assert len(yolo.get_predicted_objects(out, 0.9)) == 0
        # threshold 0 returns every anchor of every cell
        assert len(yolo.get_predicted_objects(out, 0.0)) == 2 * 4 * 4 * 2
        with pytest.raises(ValueError, match="rank 4"):
            yolo.get_predicted_objects(np.zeros((4, 4, 18)), 0.5)
        with pytest.raises(ValueError, match="threshold"):
            yolo.get_predicted_objects(out, 1.5)

    def test_confidence_and_probability_matrices(self):
        yolo = self._make()
        out, _ = yolo.forward({}, {}, self._golden_logits())
        conf = yolo.get_confidence_matrix(out, 0, 1)
        assert conf.shape == (4, 4)
        assert abs(conf[1, 2] - 1 / (1 + np.exp(-2.0))) < 1e-5
        prob = yolo.get_probability_matrix(out, 0, 3)
        assert prob.shape == (4, 4)
        assert prob[1, 2] > 0.9          # engineered class-3 peak

    def test_non_max_suppression(self):
        from deeplearning4j_tpu.nn.layers.objdetect import (
            DetectedObject, non_max_suppression)
        mk = lambda ex, cx, conf, cls: DetectedObject(  # noqa: E731
            ex, cx, 1.0, 2.0, 2.0, np.eye(4)[cls], conf)
        objs = [
            mk(0, 1.0, 0.9, 0),   # keeper
            mk(0, 1.4, 0.8, 0),   # overlaps keeper, same class → suppressed
            mk(0, 1.4, 0.7, 1),   # overlaps but different class → kept
            mk(1, 1.0, 0.6, 0),   # different example → kept
            mk(0, 8.0, 0.5, 0),   # far away → kept
        ]
        kept = non_max_suppression(objs, iou_threshold=0.3)
        assert len(kept) == 4
        assert all(k.confidence != 0.8 for k in kept)
        assert [k.confidence for k in kept] == sorted(
            [k.confidence for k in kept], reverse=True)


# ----------------------------------------------------------- dropout family
class TestDropoutFamily:
    def test_dropout_inverted_scaling(self):
        d = Dropout(p=0.8)
        x = jnp.ones((10_000,))
        y = d.apply(jax.random.PRNGKey(0), x)
        assert abs(float(y.mean()) - 1.0) < 0.05
        assert set(np.unique(np.asarray(y))) <= {0.0, np.float32(1 / 0.8)}

    def test_alpha_dropout_preserves_moments(self):
        d = AlphaDropout(p=0.9)
        x = jax.random.normal(jax.random.PRNGKey(1), (50_000,))
        y = d.apply(jax.random.PRNGKey(2), x)
        assert abs(float(y.mean())) < 0.05
        assert abs(float(y.std()) - 1.0) < 0.1

    def test_gaussian_dropout_mean(self):
        d = GaussianDropout(rate=0.5)
        x = jnp.ones((50_000,))
        y = d.apply(jax.random.PRNGKey(3), x)
        assert abs(float(y.mean()) - 1.0) < 0.05
        assert float(y.std()) > 0.5

    def test_gaussian_noise_additive(self):
        d = GaussianNoise(stddev=0.3)
        x = jnp.zeros((50_000,))
        y = d.apply(jax.random.PRNGKey(4), x)
        assert abs(float(y.std()) - 0.3) < 0.03

    def test_idropout_in_network_and_serde(self):
        conf = _mlp_conf(OutputLayer(n_in=12, n_out=3),
                         **{"dropout": GaussianDropout(rate=0.3)})
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y, epochs=2, batch_size=16)  # trains without error
        clone = MultiLayerNetwork(
            type(conf).from_json(conf.to_json())).init()
        assert isinstance(clone.layers[0].dropout, GaussianDropout)
        # inference is deterministic (no noise at test time)
        o1, o2 = net.output(x), net.output(x)
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))


# ------------------------------------------------------------- weight noise
class TestWeightNoise:
    def test_dropconnect_zeroes_weights(self):
        dc = DropConnect(p=0.5)
        params = {"W": jnp.ones((50, 50)), "b": jnp.ones((50,))}
        noised = dc.apply_params(jax.random.PRNGKey(0), params)
        frac_zero = float((noised["W"] == 0).mean())
        assert 0.3 < frac_zero < 0.7
        np.testing.assert_array_equal(np.asarray(noised["b"]), 1.0)  # bias untouched

    def test_weight_noise_training(self):
        conf = _mlp_conf(OutputLayer(n_in=12, n_out=3),
                         **{"weight_noise": WeightNoise(additive=True)})
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        s0 = float(net.score(DataSet(x, y)))
        net.fit(x, y, epochs=10, batch_size=16)
        assert float(net.score(DataSet(x, y))) < s0
        clone_conf = type(conf).from_json(conf.to_json())
        assert isinstance(clone_conf.layers[0].weight_noise, WeightNoise)


# -------------------------------------------------------------- constraints
class TestConstraints:
    def _train(self, constraint):
        conf = _mlp_conf(OutputLayer(n_in=12, n_out=3),
                         **{"constraints": [constraint]})
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.fit(x, y, epochs=3, batch_size=32)
        return np.asarray(net.params["0"]["W"])

    def test_max_norm(self):
        w = self._train(MaxNormConstraint(max_norm=0.5))
        norms = np.linalg.norm(w, axis=0)
        assert norms.max() <= 0.5 + 1e-4

    def test_unit_norm(self):
        w = self._train(UnitNormConstraint())
        norms = np.linalg.norm(w, axis=0)
        np.testing.assert_allclose(norms, 1.0, atol=1e-3)

    def test_min_max_norm(self):
        w = self._train(MinMaxNormConstraint(min_norm=0.3, max_norm=0.8))
        norms = np.linalg.norm(w, axis=0)
        assert norms.min() >= 0.3 - 1e-3 and norms.max() <= 0.8 + 1e-3

    def test_non_negative(self):
        w = self._train(NonNegativeConstraint())
        assert w.min() >= 0.0

    def test_serde(self):
        c = MinMaxNormConstraint(min_norm=0.1, max_norm=2.0, rate=0.5)
        layer = DenseLayer(n_in=3, n_out=4, constraints=[c])
        clone = layer_from_dict(layer.to_dict())
        assert clone.constraints == [c]


# ------------------------------------------------------------- upsampling1d
def test_upsampling1d():
    up = Upsampling1D(size=3)
    x = jnp.arange(2 * 4 * 5, dtype=jnp.float32).reshape(2, 4, 5)
    y, _ = up.forward({}, {}, x)
    assert y.shape == (2, 12, 5)
    np.testing.assert_array_equal(np.asarray(y[0, 0]), np.asarray(y[0, 2]))
    t = up.get_output_type(InputType.recurrent(5, 4))
    assert t.timesteps == 12


# ------------------------------- shape-op + separable layers (Keras import)
class TestShapeOpLayers:
    def test_reshape_permute_poolhelper_forward(self):
        from deeplearning4j_tpu.nn.layers import (
            PermuteLayer, PoolHelperLayer, ReshapeLayer,
        )
        x = jnp.arange(2 * 24, dtype=jnp.float32).reshape(2, 24)
        r = ReshapeLayer(target_shape=(4, 6))
        y, _ = r.forward({}, {}, x)
        assert y.shape == (2, 4, 6)
        assert r.get_output_type(InputType.feed_forward(24)).size == 6

        p = PermuteLayer(dims=(2, 1))
        z, _ = p.forward({}, {}, y)
        np.testing.assert_array_equal(np.asarray(z),
                                      np.asarray(jnp.transpose(y, (0, 2, 1))))

        c = jnp.arange(1 * 5 * 5 * 2, dtype=jnp.float32).reshape(1, 5, 5, 2)
        ph = PoolHelperLayer()
        out, _ = ph.forward({}, {}, c)
        assert out.shape == (1, 4, 4, 2)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(c[:, 1:, 1:, :]))

    def test_separable_conv_gradcheck(self):
        import jax
        from deeplearning4j_tpu.gradientcheck import check_gradients_fn
        from deeplearning4j_tpu.nn.layers import SeparableConvolution2D

        layer = SeparableConvolution2D(n_in=2, n_out=3, kernel_size=(3, 3),
                                       depth_multiplier=2, activation="tanh")
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float64)
        x = np.random.default_rng(0).standard_normal((2, 5, 5, 2))

        def loss_fn(p):
            y, _ = layer.forward(p, {}, jnp.asarray(x))
            return jnp.sum(y ** 2)

        ok, worst, fails = check_gradients_fn(loss_fn, params)
        assert ok, f"worst {worst} {fails[:3]}"

    def test_separable_conv_same_padding_shape(self):
        import jax
        from deeplearning4j_tpu.nn.layers import SeparableConvolution2D
        from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode

        layer = SeparableConvolution2D(n_in=3, n_out=4, kernel_size=(3, 3),
                                       stride=(2, 2),
                                       convolution_mode=ConvolutionMode.SAME)
        params = layer.init_params(jax.random.PRNGKey(1))
        x = jnp.zeros((1, 7, 7, 3))
        y, _ = layer.forward(params, {}, x)
        assert y.shape == (1, 4, 4, 4)
        t = layer.get_output_type(InputType.convolutional(7, 7, 3))
        assert (t.height, t.width, t.channels) == (4, 4, 4)
