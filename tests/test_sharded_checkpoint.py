"""Orbax-backed sharded checkpointing (the past-one-host replacement for
the zip ModelSerializer; see util/sharded_checkpoint.py)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import ShardedCheckpoint


def _model():
    conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(0.02))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


class TestShardedCheckpoint:
    def test_roundtrip_preserves_outputs_and_counters(self, tmp_path):
        net = _model()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.fit(x, y, epochs=3, batch_size=16)
        want = np.asarray(net.output(x))

        path = str(tmp_path / "ckpt")
        ShardedCheckpoint.save(path, net)
        clone = ShardedCheckpoint.restore(path)
        got = np.asarray(clone.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        assert clone.iteration_count == net.iteration_count
        assert clone.epoch_count == net.epoch_count

    def test_restore_with_target_shardings(self, tmp_path):
        net = _model()
        path = str(tmp_path / "ckpt")
        ShardedCheckpoint.save(path, net)

        mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
        row_sharded = NamedSharding(mesh, P("model"))
        repl = NamedSharding(mesh, P())

        # shard every 2-d param over the model axis, replicate the rest
        def spec(a):
            a = jnp.asarray(a)
            return row_sharded if (a.ndim == 2
                                   and a.shape[0] % 4 == 0) else repl

        shardings = {
            "params": jax.tree_util.tree_map(spec, net.params),
            "net_state": jax.tree_util.tree_map(spec, net.net_state),
            "updater_state": jax.tree_util.tree_map(spec, net.updater_state),
        }
        clone = ShardedCheckpoint.restore(path, shardings=shardings)
        w = clone.params["0"]["W"]                 # [8,16] sharded over rows
        assert w.sharding == row_sharded
        np.testing.assert_allclose(np.asarray(w),
                                   np.asarray(net.params["0"]["W"]),
                                   rtol=1e-6)

    def test_sharded_save_of_sharded_model(self, tmp_path):
        # params already device-sharded at save time: no host gather
        net = _model()
        mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
        row = NamedSharding(mesh, P("model"))
        net.params["0"]["W"] = jax.device_put(net.params["0"]["W"], row)
        path = str(tmp_path / "ckpt")
        ShardedCheckpoint.save(path, net)
        clone = ShardedCheckpoint.restore(path)
        np.testing.assert_allclose(np.asarray(clone.params["0"]["W"]),
                                   np.asarray(net.params["0"]["W"]),
                                   rtol=1e-6)

    def test_none_leaves_mean_default_placement(self, tmp_path):
        net = _model()
        path = str(tmp_path / "ckpt")
        ShardedCheckpoint.save(path, net)
        mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
        row = NamedSharding(mesh, P("model"))
        # shard only layer-0 W; None everywhere else
        shardings = {
            "params": jax.tree_util.tree_map(lambda a: None, net.params),
            "net_state": jax.tree_util.tree_map(lambda a: None,
                                                net.net_state),
            "updater_state": jax.tree_util.tree_map(lambda a: None,
                                                    net.updater_state),
        }
        shardings["params"]["0"]["W"] = row
        clone = ShardedCheckpoint.restore(path, shardings=shardings)
        assert clone.params["0"]["W"].sharding == row
        np.testing.assert_allclose(np.asarray(clone.params["1"]["W"]),
                                   np.asarray(net.params["1"]["W"]))

    def test_no_meta_side_file(self, tmp_path):
        # meta rides inside the atomic composite, not as a torn-off file
        import os
        net = _model()
        path = str(tmp_path / "ckpt")
        ShardedCheckpoint.save(path, net)
        assert not os.path.exists(os.path.join(path, "meta.json"))
        assert os.path.isdir(os.path.join(path, "meta"))
