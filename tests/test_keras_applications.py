"""Reference-scale pretrained proof: genuine keras-applications
architectures + externally-produced weight files flow through the zoo
`init_pretrained` path with golden activation parity against Keras
itself (reference `ZooModel.java:52-81` + `KerasModelImport.java`).

Offline protocol (zero-egress sandbox): the weights are generated at
test time by the REAL keras 3 library — the genuine keras-applications
ResNet50/VGG16 graphs, saved in the exact legacy HDF5 layout the
keras-applications download distributes (`legacy_h5_format`) — and
served to `init_pretrained` through a file:// URL with a real md5
checksum. Everything from the checksum gate to the name-matched weight
copy is the production path; only the transport is local. The hosted
URLs + published md5s stay wired in the zoo classes for online use.

Marked slow: building keras models + a 550 MB VGG16 h5 costs ~2-4 min.
"""

import hashlib
import os

import numpy as np
import pytest

keras = pytest.importorskip("keras")

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tmp_cache(tmp_path_factory):
    """Redirect the zoo download cache to a disposable dir."""
    import deeplearning4j_tpu.zoo.base as zoo_base
    old = zoo_base.CACHE_DIR
    zoo_base.CACHE_DIR = tmp_path_factory.mktemp("zoo_cache")
    yield zoo_base.CACHE_DIR
    zoo_base.CACHE_DIR = old


def _legacy_weights_h5(model, path):
    import h5py
    from keras.src.legacy.saving import legacy_h5_format
    with h5py.File(path, "w") as f:
        legacy_h5_format.save_weights_to_hdf5_group(f, model)


def _serve(zoo, path):
    md5 = hashlib.md5(open(path, "rb").read()).hexdigest()
    zoo.pretrained_url = lambda p: f"file://{path}"
    zoo.pretrained_checksum = lambda p: md5
    return zoo


class TestKerasApplicationsPretrained:
    def test_resnet50_weights_only_through_init_pretrained(
            self, tmp_path, tmp_cache):
        """Full-depth keras-applications ResNet50 (107 weighted
        tensors, ZeroPadding + biased convs + BN): weights-only legacy
        h5 routed through the committed architecture JSON, golden
        activation parity vs keras' own forward."""
        from deeplearning4j_tpu.zoo.base import PretrainedType
        from deeplearning4j_tpu.zoo.resnet50 import ResNet50

        keras.utils.set_random_seed(0)
        km = keras.applications.ResNet50(weights=None)
        wpath = tmp_path / "rn50_w.h5"
        _legacy_weights_h5(km, wpath)
        rng = np.random.default_rng(5)
        x = rng.standard_normal((1, 224, 224, 3)).astype(np.float32)
        want = km.predict(x, verbose=0)

        net = _serve(ResNet50(), wpath).init_pretrained(
            PretrainedType.IMAGENET)
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, atol=1e-6)
        assert int(np.argmax(got)) == int(np.argmax(want))

    def test_resnet50_full_model_h5_import(self, tmp_path):
        """The one-file route: keras `model.save(.h5)` (config +
        weights) → KerasModelImport → same activations."""
        from deeplearning4j_tpu.modelimport import KerasModelImport

        keras.utils.set_random_seed(0)
        km = keras.applications.ResNet50(weights=None)
        mpath = tmp_path / "rn50_full.h5"
        km.save(mpath)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((1, 224, 224, 3)).astype(np.float32)
        want = km.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(str(mpath))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_vgg16_weights_only_into_zoo_architecture(
            self, tmp_path, tmp_cache):
        """VGG16: the zoo's OWN builder is keras-compatible (16
        weighted layers, stride-1 SAME convs), so the weights-only
        payload order-matches into it — the `ZooModel.initPretrained`
        route the reference serves VGG16 ImageNet weights through."""
        from deeplearning4j_tpu.zoo.base import PretrainedType
        from deeplearning4j_tpu.zoo.vgg import VGG16

        keras.utils.set_random_seed(1)
        km = keras.applications.VGG16(weights=None)
        wpath = tmp_path / "vgg16_w.h5"
        _legacy_weights_h5(km, wpath)
        rng = np.random.default_rng(5)
        x = rng.standard_normal((1, 224, 224, 3)).astype(np.float32)
        want = km.predict(x, verbose=0)

        net = _serve(VGG16(), wpath).init_pretrained(
            PretrainedType.IMAGENET)
        got = np.asarray(net.output(x))
        # 138M params of fp32 reduction-order noise through fc1's
        # 25088-term dots: random-weight probabilities are near-uniform
        # (~1e-3 each), so neither argmax nor centered correlation is
        # meaningful at the softmax — the 5e-5 absolute bound (20x
        # tighter than a scrambled-weight outcome) plus exact
        # first-conv parity below pin the weight placement
        np.testing.assert_allclose(got, want, atol=5e-5)
        # first conv activations: one layer of accumulation → tight
        # cross-framework parity proves block1_conv1 weights landed
        sub = keras.Model(km.inputs, km.layers[1].output)
        want_c1 = sub.predict(x, verbose=0)
        got_c1 = np.asarray(net.feed_forward(x)[0])
        np.testing.assert_allclose(got_c1, want_c1, atol=1e-4)

    def test_checksum_gate_rejects_corruption(self, tmp_path, tmp_cache):
        from deeplearning4j_tpu.zoo.base import PretrainedType
        from deeplearning4j_tpu.zoo.resnet50 import ResNet50

        bad = tmp_path / "bad.h5"
        bad.write_bytes(b"\x89HDF\r\n\x1a\njunk")
        zoo = ResNet50()
        zoo.pretrained_url = lambda p: f"file://{bad}"
        zoo.pretrained_checksum = lambda p: "0" * 32   # wrong md5
        with pytest.raises(IOError, match="Checksum mismatch"):
            zoo.init_pretrained(PretrainedType.IMAGENET)

    def test_hosted_urls_and_hashes_stay_wired(self):
        """The online route: official keras-applications URLs + the
        md5s keras publishes (`keras.src.applications` WEIGHTS_HASHES)
        remain declared on the zoo classes."""
        from deeplearning4j_tpu.zoo.base import PretrainedType
        from deeplearning4j_tpu.zoo.resnet50 import ResNet50
        from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19

        rn = ResNet50()
        assert rn.pretrained_url(PretrainedType.IMAGENET).startswith(
            "https://storage.googleapis.com/tensorflow/keras-applications/")
        assert rn.pretrained_checksum(PretrainedType.IMAGENET) == \
            "2cb95161c43110f7111970584f804107"
        assert rn.keras_architecture[PretrainedType.IMAGENET] == \
            "resnet50_keras_arch.json"
        arch = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..",
            "deeplearning4j_tpu", "zoo", "weights",
            "resnet50_keras_arch.json")
        assert os.path.exists(arch)
        assert VGG16().pretrained_checksum(PretrainedType.IMAGENET) == \
            "64373286793e3c8b2b4e3219cbf3544b"
        assert VGG19().pretrained_checksum(PretrainedType.IMAGENET) == \
            "cbe5617147190e668d6c5d5026f83318"
