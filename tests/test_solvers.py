"""Line-search solver family tests (reference
`optimize/solvers/BaseOptimizer.java`, `BackTrackLineSearch.java`:
convex convergence + small-MLP fit through the builder selector)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.solvers import (
    BackTrackLineSearch,
    ConjugateGradient,
    LBFGS,
    LineGradientDescent,
    NegativeDefaultStepFunction,
    OptimizationAlgorithm,
    Solver,
    step_function_from_dict,
)

SOLVERS = [LineGradientDescent, ConjugateGradient, LBFGS]


def rosenbrock(x):
    return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1 - x[:-1]) ** 2)


class TestSolversConvex:
    @pytest.mark.parametrize("cls", SOLVERS)
    def test_quadratic_converges(self, cls):
        # f(x) = 0.5 xᵀAx - bᵀx, A SPD — unique minimum at A⁻¹b
        rng = np.random.default_rng(0)
        M = rng.standard_normal((6, 6))
        A = jnp.asarray(M @ M.T + 6 * np.eye(6), jnp.float32)
        b = jnp.asarray(rng.standard_normal(6), jnp.float32)

        def f(x):
            return 0.5 * x @ A @ x - b @ x

        opt = cls(max_iterations=200, tolerance=1e-12)
        x = opt.optimize(f, jnp.zeros(6))
        x_star = jnp.linalg.solve(A, b)
        np.testing.assert_allclose(np.asarray(x), np.asarray(x_star),
                                   rtol=1e-3, atol=1e-3)
        # scores strictly decrease overall
        assert opt.scores[-1] < opt.scores[0]

    @pytest.mark.parametrize("cls", [ConjugateGradient, LBFGS])
    def test_rosenbrock_progress(self, cls):
        opt = cls(max_iterations=300, tolerance=1e-14)
        x = opt.optimize(rosenbrock, jnp.zeros(4))
        assert float(rosenbrock(x)) < 1e-2

    def test_lbfgs_beats_gd_on_illconditioned(self):
        # ill-conditioned quadratic: curvature memory must pay off
        d = jnp.asarray(np.logspace(0, 3, 10), jnp.float32)

        def f(x):
            return 0.5 * jnp.sum(d * x ** 2)

        x0 = jnp.ones(10)
        gd = LineGradientDescent(max_iterations=25, tolerance=0)
        lb = LBFGS(max_iterations=25, tolerance=0)
        f_gd = float(f(gd.optimize(f, x0)))
        f_lb = float(f(lb.optimize(f, x0)))
        assert f_lb < f_gd


class TestBackTrackLineSearch:
    def test_accepts_descent_step(self):
        f = lambda x: jnp.sum(x ** 2)
        x = jnp.asarray([3.0])
        g = jax.grad(lambda x: jnp.sum(x ** 2))(x)
        ls = BackTrackLineSearch()
        alpha, f_new = ls.optimize(f, x, float(f(x)), g, -g)
        assert alpha > 0
        assert f_new < float(f(x))

    def test_rejects_ascent_direction(self):
        f = lambda x: jnp.sum(x ** 2)
        x = jnp.asarray([3.0])
        g = jax.grad(lambda x: jnp.sum(x ** 2))(x)
        ls = BackTrackLineSearch()
        alpha, f_new = ls.optimize(f, x, float(f(x)), g, g)  # uphill
        assert alpha == 0.0

    def test_step_function_serde(self):
        sf = NegativeDefaultStepFunction()
        rt = step_function_from_dict(sf.to_dict())
        assert type(rt) is NegativeDefaultStepFunction
        x = jnp.asarray([1.0])
        np.testing.assert_allclose(np.asarray(rt.step(x, jnp.asarray([2.0]), 0.5)),
                                   [0.0])


class TestSolverOnModel:
    def _net(self, algo=None, max_iter=5):
        b = NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
        if algo is not None:
            b = b.optimization_algo(algo).max_iterations(max_iter)
        conf = (b.list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def _data(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = np.eye(3)[rng.integers(0, 3, 32)].astype(np.float32)
        return x, y

    @pytest.mark.parametrize("algo", [OptimizationAlgorithm.CONJUGATE_GRADIENT,
                                      OptimizationAlgorithm.LBFGS,
                                      OptimizationAlgorithm.LINE_GRADIENT_DESCENT])
    def test_solver_reduces_model_loss(self, algo):
        net = self._net()
        x, y = self._data()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        before = net.score(DataSet(x, y))
        s = Solver(net, algo, max_iterations=20)
        after = s.optimize(x, y)
        assert after < before
        assert net.score(DataSet(x, y)) == pytest.approx(after, rel=1e-4)

    def test_builder_selector_routes_fit(self):
        net = self._net(algo="lbfgs", max_iter=10)
        assert net.conf.optimization_algo == "lbfgs"
        x, y = self._data()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        before = net.score(DataSet(x, y))
        net.fit(x, y, epochs=2, batch_size=32)
        assert net.score(DataSet(x, y)) < before

    def test_conf_serde_roundtrip(self):
        net = self._net(algo="conjugate_gradient", max_iter=7)
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert conf2.optimization_algo == "conjugate_gradient"
        assert conf2.max_iterations == 7

    def test_solver_on_computation_graph(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(5))
        g.add_inputs("in")
        g.add_layer("fc", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                       loss="mcxent"), "fc")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        x, y = self._data()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        before = net.score(DataSet(x, y))
        after = Solver(net, "lbfgs", max_iterations=20).optimize(x, y)
        assert after < before

    def test_graph_builder_selector_routes_fit(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(5)
            .optimization_algo("conjugate_gradient").max_iterations(10))
        g.add_inputs("in")
        g.add_layer("fc", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                       loss="mcxent"), "fc")
        g.set_outputs("out")
        conf = g.build()
        assert conf.optimization_algo == "conjugate_gradient"
        # serde keeps it
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert conf2.optimization_algo == "conjugate_gradient"
        net = ComputationGraph(conf).init()
        x, y = self._data()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        before = net.score(DataSet(x, y))
        net.fit(x, y, epochs=2, batch_size=32)
        assert net.score(DataSet(x, y)) < before

    def test_solver_respects_masks(self):
        # masked-out padded timesteps must not affect the solved loss
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.builder().seed(11).list()
                .layer(LSTM(n_in=3, n_out=6))
                .layer(RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(4)
        x_short = rng.standard_normal((2, 3, 3)).astype(np.float32)
        y_short = np.eye(2)[rng.integers(0, 2, (2, 3))].astype(np.float32)
        x_pad = np.concatenate([x_short, 99 * np.ones((2, 2, 3), np.float32)], 1)
        y_pad = np.concatenate([y_short, np.zeros((2, 2, 2), np.float32)], 1)
        mask = np.concatenate([np.ones((2, 3)), np.zeros((2, 2))], 1).astype(np.float32)

        s1 = Solver(net, "lbfgs", max_iterations=0)
        loss_short = s1.optimize(x_short, y_short)
        net2 = MultiLayerNetwork(conf).init()
        s2 = Solver(net2, "lbfgs", max_iterations=0)
        loss_pad = s2.optimize(x_pad, y_pad, fmask=mask, lmask=mask)
        np.testing.assert_allclose(loss_short, loss_pad, rtol=1e-5)
