"""UI/observability tests (reference: UI server smoke tests, storage
round-trips, SBE encode/decode tests)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    StatsListener,
    StatsReport,
    UIServer,
)


def make_report(it=3, score=0.5):
    return StatsReport(
        session_id="s1", worker_id="w0", iteration=it, epoch=0,
        timestamp=123.0, score=score, iteration_time_ms=10.0,
        examples_per_sec=100.0,
        param_mean_magnitudes={"0_W": 0.12, "0_b": 0.01},
        update_mean_magnitudes={"0_W": 0.001},
        param_histograms={"0_W": ([0.0, 0.5, 1.0], [3, 7])},
        memory_rss_mb=256.0)


class TestWireFormat:
    def test_roundtrip(self):
        r = make_report()
        back = StatsReport.decode(r.encode())
        assert back.session_id == "s1" and back.iteration == 3
        assert back.param_mean_magnitudes == r.param_mean_magnitudes
        assert back.param_histograms["0_W"][1] == [3, 7]
        assert back.memory_rss_mb == 256.0


class TestStorage:
    def test_in_memory(self):
        st = InMemoryStatsStorage()
        st.put_report(make_report(1))
        st.put_report(make_report(2))
        assert st.list_session_ids() == ["s1"]
        assert [r.iteration for r in st.get_reports("s1")] == [1, 2]
        assert st.latest_report("s1").iteration == 2

    def test_sqlite_roundtrip(self, tmp_path):
        st = FileStatsStorage(tmp_path / "stats.db")
        st.put_report(make_report(1, 0.9))
        st.put_report(make_report(5, 0.4))
        st2 = FileStatsStorage(tmp_path / "stats.db")  # reopen
        reports = st2.get_reports("s1")
        assert [r.iteration for r in reports] == [1, 5]
        assert reports[1].score == 0.4


class TestListenerAndServer:
    def _train_with(self, listener):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init().set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
        net.fit(x, y, epochs=3, batch_size=32)

    def test_stats_listener_collects(self):
        storage = InMemoryStatsStorage()
        self._train_with(StatsListener(storage, session_id="train1",
                                       collect_histograms=True))
        reports = storage.get_reports("train1")
        assert len(reports) == 6  # 3 epochs x 2 batches
        assert "0_W" in reports[0].param_mean_magnitudes
        assert "1_W" in reports[0].param_histograms
        assert reports[-1].memory_rss_mb > 0

    def test_server_pages_and_api(self):
        storage = InMemoryStatsStorage()
        self._train_with(StatsListener(storage, session_id="ui_sess"))
        server = UIServer().attach(storage).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            for page in ("/train/overview", "/train/model", "/train/system"):
                html = urllib.request.urlopen(base + page).read().decode()
                assert "ui_sess" in html
            api = json.loads(urllib.request.urlopen(
                base + "/api/reports/ui_sess").read())
            assert len(api) == 6 and "score" in api[0]
        finally:
            server.stop()

    def test_remote_router(self):
        server = UIServer().start()  # own in-memory storage
        try:
            router = RemoteUIStatsStorageRouter(
                f"http://127.0.0.1:{server.port}")
            router.put_report(make_report(7))
            reports = server.storage.get_reports("s1")
            assert len(reports) == 1 and reports[0].iteration == 7
        finally:
            server.stop()


class TestConvListener:
    def test_saves_activation_grids(self, tmp_path):
        from deeplearning4j_tpu.ui import ConvolutionalIterationListener
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer, SubsamplingLayer

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        net = MultiLayerNetwork(conf).init().set_listeners(
            ConvolutionalIterationListener(tmp_path, frequency=1))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 8, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        net.fit(x, y, epochs=1, batch_size=4)
        pngs = list(tmp_path.glob("*.png"))
        assert len(pngs) >= 1  # at least the conv layer grid


class TestComponents:
    """ui-components equivalent (reference `components/chart/Chart.java`
    family): JSON round-trip + self-contained rendering."""

    def test_chart_line_roundtrip_and_render(self):
        from deeplearning4j_tpu.ui import ChartLine, component_from_json
        c = ChartLine(title="loss")
        c.add_series("train", [0, 1, 2], [3.0, 2.0, 1.5])
        c.add_series("val", [0, 1, 2], [3.2, 2.5, 2.0])
        rt = component_from_json(c.to_json())
        assert rt.to_dict() == c.to_dict()
        svg = rt.render()
        assert svg.count("<polyline") == 2 and "loss" in svg

    def test_chart_histogram_roundtrip_and_render(self):
        from deeplearning4j_tpu.ui import ChartHistogram, component_from_dict
        h = ChartHistogram(title="weights")
        for i in range(5):
            h.add_bin(i, i + 1, 10 * i)
        rt = component_from_dict(h.to_dict())
        assert rt.to_dict() == h.to_dict()
        assert rt.render().count("<rect") >= 5  # bg + bins

    def test_chart_scatter_labels(self):
        from deeplearning4j_tpu.ui import ChartScatter, component_from_dict
        s = ChartScatter(title="tsne")
        s.add_series("pts", [0.0, 1.0], [0.0, 1.0], ["a", "b"])
        rt = component_from_dict(s.to_dict())
        svg = rt.render()
        assert svg.count("<circle") == 2 and ">a</text>" in svg

    def test_table_text_div(self):
        from deeplearning4j_tpu.ui import (
            ComponentDiv, ComponentTable, ComponentText, component_from_dict,
        )
        div = ComponentDiv(ComponentText("hello"),
                           ComponentTable(["k", "v"], [["a", 1]], title="t"))
        rt = component_from_dict(div.to_dict())
        html = rt.render()
        assert "hello" in html and "<table" in html and "<h4>t</h4>" in html


class TestUIModules:
    def _train_with_stats(self, server):
        storage = server.storage
        listener = StatsListener(storage, session_id="s-mod",
                                 collect_histograms=True)
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init().set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        net.fit(x, y, epochs=3, batch_size=8)

    def test_model_drilldown_page(self):
        import urllib.request
        server = UIServer().start()
        try:
            self._train_with_stats(server)
            html = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/train/model").read().decode()
            # per-layer timeline charts + histograms + table
            assert "mean |param|" in html
            assert "<polyline" in html
            assert "distribution" in html and "<rect" in html
            assert "latest parameter magnitudes" in html
            # update magnitudes appear after the first report
            assert "Δ" in html
        finally:
            server.stop()

    def test_system_page_has_timing(self):
        import urllib.request
        server = UIServer().start()
        try:
            self._train_with_stats(server)
            html = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/train/system").read().decode()
            assert "RSS MB" in html and "ms/iter" in html
        finally:
            server.stop()

    def test_tsne_module_upload_and_render(self):
        import urllib.request
        server = UIServer().start()
        try:
            payload = json.dumps({
                "session": "emb", "coords": [[0.0, 0.0], [1.0, 2.0]],
                "labels": ["cat", "dog"]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/tsne/upload", data=payload,
                method="POST")
            assert json.loads(urllib.request.urlopen(req).read())["status"] == "ok"
            html = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/tsne").read().decode()
            assert "t-SNE — emb" in html and "cat" in html and "<circle" in html
        finally:
            server.stop()

    def test_tsne_rejects_bad_coords(self):
        import urllib.error
        import urllib.request
        server = UIServer().start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/tsne/upload",
                data=json.dumps({"coords": [1, 2, 3]}).encode(),
                method="POST")
            try:
                urllib.request.urlopen(req)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.stop()

    def test_activations_module(self):
        import urllib.request
        server = UIServer().start()
        try:
            grid = (np.arange(64).reshape(8, 8) * 3).astype(np.uint8)
            server.post_activation_grid("layer0", grid)
            html = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/activations").read().decode()
            assert "layer0" in html and "data:image/png;base64," in html
            png = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/activations/img/layer0").read()
            assert png[:8] == b"\x89PNG\r\n\x1a\n"
        finally:
            server.stop()

    def test_conv_listener_feeds_ui_server(self):
        from deeplearning4j_tpu.ui import ConvolutionalIterationListener
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer
        server = UIServer().start()
        try:
            conf = (NeuralNetConfiguration.builder().seed(0)
                    .updater(Adam(1e-2)).list()
                    .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                            activation="relu"))
                    .layer(OutputLayer(n_out=2))
                    .set_input_type(InputType.convolutional(8, 8, 1)).build())
            net = MultiLayerNetwork(conf).init().set_listeners(
                ConvolutionalIterationListener(frequency=1, ui_server=server))
            rng = np.random.default_rng(0)
            x = rng.standard_normal((4, 8, 8, 1)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
            net.fit(x, y, epochs=1, batch_size=4)
            assert "layer0" in server._activations
        finally:
            server.stop()

    def test_components_api_json(self):
        import urllib.request
        from deeplearning4j_tpu.ui import component_from_json
        server = UIServer().start()
        try:
            self._train_with_stats(server)
            raw = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/components/s-mod"
            ).read().decode()
            chart = component_from_json(raw)
            assert chart.series and chart.series[0][0] == "score"
        finally:
            server.stop()

    def test_tsne_labels_are_escaped(self):
        import urllib.request
        server = UIServer().start()
        try:
            payload = json.dumps({
                "session": "x", "coords": [[0.0, 0.0]],
                "labels": ["</text><script>alert(1)</script>"]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/tsne/upload", data=payload,
                method="POST")
            urllib.request.urlopen(req)
            html = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/tsne").read().decode()
            assert "<script>" not in html
            assert "&lt;script&gt;" in html
        finally:
            server.stop()

    def test_tsne_rejects_empty_coords(self):
        server = UIServer()
        with pytest.raises(ValueError):
            server.post_tsne("s", np.zeros((0, 2)))


class TestDashboardDepth:
    """Round-4 TrainModule-depth features: update:param ratio chart,
    i18n (?lang=), auto-refresh (?refresh=) — reference
    `module/train/TrainModule.java:93-105` + play i18n bundles."""

    def _server_with_data(self):
        import numpy as np
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.stats import StatsReport

        server = UIServer(0).start()
        for it in (0, 10, 20):
            server.storage.put_report(StatsReport(
                session_id="s1", worker_id="w0", iteration=it, epoch=0,
                timestamp=float(it), score=1.0 / (it + 1),
                examples_per_sec=100.0,
                param_mean_magnitudes={"0_W": 0.5},
                update_mean_magnitudes=({"0_W": 0.005} if it else {}),
            ))
        return server

    def _get(self, server, path):
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{path}") as r:
            return r.read().decode()

    def test_update_param_ratio_chart_rendered(self):
        server = self._server_with_data()
        try:
            html = self._get(server, "/train/model")
            assert "update : param ratio" in html
            # log10(0.005/0.5) = -2 must appear as a plotted series
            assert "0_W" in html
        finally:
            server.stop()

    def test_lang_parameter_localizes_and_propagates(self):
        server = self._server_with_data()
        try:
            html = self._get(server, "/train/overview?lang=ja")
            assert "学習の概要" in html           # localized title
            assert 'href="/train/model?lang=ja"' in html  # nav keeps lang
            html_zh = self._get(server, "/train/model?lang=zh")
            assert "更新:参数比" in html_zh
        finally:
            server.stop()

    def test_refresh_parameter_adds_meta_tag(self):
        server = self._server_with_data()
        try:
            html = self._get(server, "/train/overview?refresh=5")
            assert '<meta http-equiv="refresh" content="5">' in html
            plain = self._get(server, "/train/overview")
            assert "http-equiv" not in plain
        finally:
            server.stop()

    def test_unknown_lang_falls_back_to_english(self):
        server = self._server_with_data()
        try:
            html = self._get(server, "/train/overview?lang=xx")
            assert "Training Overview" in html
        finally:
            server.stop()

    def test_lang_is_whitelisted_not_reflected(self):
        """lang is echoed into hrefs, so arbitrary values must never
        round-trip (reflected-XSS vector): unknown values normalize to
        'en' and do not appear in the page."""
        server = self._server_with_data()
        try:
            html = self._get(server,
                             "/train/overview?lang=%22%3E%3Cb%3E")
            assert '"><b>' not in html
            assert 'href="/train/model"' in html  # qs dropped entirely
        finally:
            server.stop()


class TestProfileRoute:
    def _get(self, server, path):
        import urllib.request
        return urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=10).read().decode()

    def test_profile_renders_published_report(self):
        from deeplearning4j_tpu.monitor import xprof
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry

        xprof.clear_cost_reports()
        xprof.publish_cost_report({
            "model": "demo_model",
            "per_op": {"total_flops_per_step": 1.0e9,
                       "total_bytes_per_step": 2.0e8,
                       "conv_dot_flops_per_step": 9.0e8,
                       "top10": [{"op": "dot_general",
                                  "shape": "f32[8,4] -> f32[8,8]",
                                  "flops": 9.0e8, "bytes": 1e6,
                                  "share": 0.9}]},
            "roofline": {"arithmetic_intensity_flop_per_byte": 5.0,
                         "bound": "memory", "peak_tflops": 111.4,
                         "peak_source": "test"},
            "predicted": {"step_seconds": 0.01, "mfu": 0.2,
                          "mfu_if_compute_bound": 0.9},
        }, registry=MetricsRegistry())
        server = UIServer().start()
        try:
            html = self._get(server, "/profile")
            assert "demo_model" in html
            assert "dot_general" in html
            api = json.loads(self._get(server, "/api/profile"))
            assert api["demo_model"]["predicted"]["mfu"] == 0.2
        finally:
            server.stop()
            xprof.clear_cost_reports()

    def test_profile_empty_shows_hint(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.monitor import xprof

        xprof.clear_cost_reports()
        monkeypatch.chdir(tmp_path)   # no PROFILE_* artifacts to scan
        server = UIServer().start()
        try:
            html = self._get(server, "/profile")
            assert "benchtools.hlo_cost" in html
        finally:
            server.stop()
