"""Viterbi decoder, DiskBasedQueue, and streaming routes (reference
`util/Viterbi.java`, `util/DiskBasedQueue.java`,
`streaming/routes/DL4jServeRouteBuilder.java`)."""

import numpy as np

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.streaming import (
    LocalQueueTransport,
    NDArrayConsumer,
    NDArrayPublisher,
    RecordPublishRoute,
    ServingRoute,
)
from deeplearning4j_tpu.util import DiskBasedQueue, Viterbi, viterbi_decode


class TestViterbi:
    def test_smooths_isolated_flips(self):
        # metastable truth: 0 x10, then 1 x10, with two isolated flips
        obs = np.array([0] * 10 + [1] * 10)
        obs[4] = 1
        obs[14] = 0
        v = Viterbi(num_states=2, p_correct=0.9, meta_stability=0.95)
        score, path = v.decode(obs)
        assert path.tolist() == [0] * 10 + [1] * 10
        assert score < 0  # log prob

    def test_binary_label_matrix_input(self):
        obs = np.eye(3)[[2, 2, 0, 2, 2]]
        v = Viterbi(num_states=3, p_correct=0.8, meta_stability=0.9)
        _, path = v.decode(obs)
        assert path.tolist() == [2, 2, 2, 2, 2]

    def test_trusts_observations_when_emission_sharp(self):
        obs = np.array([0, 1, 0, 1, 0])
        v = Viterbi(num_states=2, p_correct=0.9999, meta_stability=0.6)
        _, path = v.decode(obs)
        assert path.tolist() == obs.tolist()

    def test_general_hmm_decode(self):
        # 2-state HMM where the sharp middle emission outweighs the two
        # transitions it costs (0.9*0.2*0.98*0.2*0.9 > 0.9*0.8*0.02*0.8*0.9)
        log_em = np.log(np.array([[0.9, 0.1], [0.02, 0.98], [0.9, 0.1]]))
        log_tr = np.log(np.array([[0.8, 0.2], [0.2, 0.8]]))
        score, path = viterbi_decode(log_em, log_tr)
        assert path.tolist() == [0, 1, 0]


class TestDiskBasedQueue:
    def test_fifo_spill_and_restore(self):
        with DiskBasedQueue() as q:
            for i in range(5):
                q.add({"i": i, "arr": np.arange(i)})
            assert q.size() == 5
            assert q.peek()["i"] == 0
            out = [q.poll()["i"] for _ in range(5)]
            assert out == [0, 1, 2, 3, 4]
            assert q.poll() is None
            assert q.is_empty()

    def test_memory_window(self, tmp_path):
        import os
        q = DiskBasedQueue(str(tmp_path), memory_window=2)
        q.add_all([1, 2, 3, 4])
        assert len(os.listdir(tmp_path)) == 2   # only 3,4 spilled
        assert list(q) == [1, 2, 3, 4]

    def test_remove_raises_on_empty(self, tmp_path):
        import pytest
        q = DiskBasedQueue(str(tmp_path))
        with pytest.raises(IndexError):
            q.remove()


class TestNDArrayWireDtypes:
    """The request-plane payload contract: bf16 (serving activations /
    mixed_bf16 wire) and int8 (quantized payloads) ride the ND4T wire
    byte-exactly; an unknown dtype code fails NAMING the code."""

    def test_bf16_roundtrip(self):
        from ml_dtypes import bfloat16
        from deeplearning4j_tpu.streaming.ndarray import (
            deserialize_ndarray, serialize_ndarray)
        a = np.random.default_rng(0).standard_normal(
            (3, 5)).astype(bfloat16)
        b = deserialize_ndarray(serialize_ndarray(a))
        assert b.dtype == np.dtype(bfloat16)
        assert b.tobytes() == a.tobytes()        # bit-exact, no up-cast

    def test_int8_roundtrip(self):
        from deeplearning4j_tpu.streaming.ndarray import (
            deserialize_ndarray, serialize_ndarray)
        a = np.random.default_rng(1).integers(
            -128, 128, (4, 7), dtype=np.int8)
        b = deserialize_ndarray(serialize_ndarray(a))
        assert b.dtype == np.int8
        np.testing.assert_array_equal(a, b)

    def test_transport_carries_new_dtypes(self):
        from ml_dtypes import bfloat16
        tr = LocalQueueTransport()
        pub = NDArrayPublisher(tr, "t")
        sub = NDArrayConsumer(tr, "t")
        for arr in (np.ones((2, 2), bfloat16) * 1.5,
                    np.arange(-4, 4, dtype=np.int8)):
            pub.publish(arr)
            out = sub.consume(timeout=1.0)
            assert out.dtype == arr.dtype
            assert out.tobytes() == arr.tobytes()

    def test_unknown_code_error_names_the_code(self):
        import pytest
        from deeplearning4j_tpu.streaming.ndarray import (
            deserialize_ndarray, serialize_ndarray)
        data = bytearray(serialize_ndarray(np.zeros(2, np.float32)))
        data[4] = 250                       # forge a future dtype code
        with pytest.raises(ValueError, match="code 250"):
            deserialize_ndarray(bytes(data))

    def test_unsupported_dtype_serialize_rejected(self):
        import pytest
        from deeplearning4j_tpu.streaming.ndarray import serialize_ndarray
        with pytest.raises(TypeError, match="float16"):
            serialize_ndarray(np.zeros(2, np.float16))


def _trained_xor_net():
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.1)).list()
            .layer(DenseLayer(n_in=2, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, epochs=150, batch_size=4, shuffle=False)
    return net, x


class TestServingRoute:
    def test_end_to_end_local_transport(self):
        net, x = _trained_xor_net()
        tr = LocalQueueTransport()
        route = ServingRoute(tr, "in", "out", model=net)
        pub = NDArrayPublisher(tr, "in")
        sub = NDArrayConsumer(tr, "out")
        for row in x:
            pub.publish(row[None, :])
        served = route.run(max_messages=10, timeout=0.1)
        assert served == 4
        outs = [sub.consume(timeout=0.5) for _ in range(4)]
        preds = [int(np.argmax(o)) for o in outs]
        assert preds == [0, 1, 1, 0]

    def test_before_and_final_processors(self):
        net, x = _trained_xor_net()
        tr = LocalQueueTransport()
        route = ServingRoute(
            tr, "in", "out", model=net,
            before=lambda a: a.reshape(1, -1),
            final=lambda a: np.argmax(a, axis=-1).astype(np.float32))
        NDArrayPublisher(tr, "in").publish(x[1])    # 1-d record
        assert route.run(max_messages=1, timeout=0.1) == 1
        out = NDArrayConsumer(tr, "out").consume(timeout=0.5)
        assert out.tolist() == [1.0]

    def test_model_uri_lazy_restore(self, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer
        net, x = _trained_xor_net()
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path)
        tr = LocalQueueTransport()
        route = ServingRoute(tr, "in", "out", model_uri=path)
        NDArrayPublisher(tr, "in").publish(x)
        assert route.run(max_messages=1, timeout=0.1) == 1
        out = NDArrayConsumer(tr, "out").consume(timeout=0.5)
        assert out.shape == (4, 2)

    def test_background_thread_serving(self):
        net, x = _trained_xor_net()
        tr = LocalQueueTransport()
        route = ServingRoute(tr, "in", "out", model=net).start(
            poll_timeout=0.05)
        try:
            pub = NDArrayPublisher(tr, "in")
            sub = NDArrayConsumer(tr, "out")
            pub.publish(x)
            out = sub.consume(timeout=5.0)
            assert out.shape == (4, 2)
        finally:
            route.stop()

    def test_record_publish_route(self):
        tr = LocalQueueTransport()
        rp = RecordPublishRoute(tr, "records")
        n = rp.publish([[1.0, 2.0], [3.0, 4.0]])
        assert n == 2
        sub = NDArrayConsumer(tr, "records")
        a = sub.consume(timeout=0.5)
        assert a.tolist() == [1.0, 2.0]


def test_diskqueue_preserves_none_payload(tmp_path):
    q = DiskBasedQueue(str(tmp_path))
    q.add(1)
    q.add(None)
    q.add(2)
    assert list(q) == [1, None, 2]


def test_serving_route_propagates_transport_errors():
    from deeplearning4j_tpu.streaming.ndarray import Transport

    class BrokenTransport(Transport):
        def send(self, topic, payload):
            pass

        def receive(self, topic, timeout=None):
            raise ConnectionError("broker down")

    net, _ = _trained_xor_net()
    route = ServingRoute(BrokenTransport(), "in", "out", model=net)
    import pytest
    with pytest.raises(ConnectionError):
        route.process_one(timeout=0.1)
