"""CJK segmentation through the TokenizerFactory seam (reference role:
deeplearning4j-nlp-chinese / -japanese bundle real segmenters behind
TokenizerFactory). The segmenter here is the in-repo dictionary-DP one
(`nlp/cjk.py`); these tests prove a NON-whitespace tokenizer actually
drives vocabulary construction and Word2Vec training end-to-end —
whitespace splitting would yield whole-sentence "words" and no
co-occurrence structure at all."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.cjk import (
    CJKTokenizerFactory,
    DictionarySegmenter,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

# Small real-Chinese lexicon: animals / food / finance topic words +
# function words, with frequencies favoring multi-char dictionary words.
LEXICON = {
    "猫": 50, "狗": 50, "兔子": 30, "动物": 40, "宠物": 30,
    "吃": 60, "喜欢": 60, "鱼": 40, "肉": 40, "米饭": 30, "苹果": 30,
    "银行": 40, "股票": 40, "市场": 40, "价格": 30, "经济": 30,
    "上涨": 20, "下跌": 20, "投资": 25,
    "我": 80, "的": 100, "在": 60, "和": 60, "了": 60, "很": 40,
    "今天": 30, "可爱": 25, "跑": 20, "玩": 25, "公园": 20,
}


def corpus():
    animals = [
        "我的猫喜欢吃鱼",
        "狗在公园跑和玩",
        "兔子很可爱",
        "猫和狗是宠物动物" if False else "猫和狗很可爱",
        "我喜欢我的狗",
        "宠物猫吃鱼和肉",
        "兔子吃苹果",
        "狗喜欢吃肉",
        "可爱的猫在玩",
        "动物喜欢在公园玩",
    ]
    finance = [
        "股票价格上涨了",
        "银行和市场的经济",
        "投资股票的价格",
        "市场价格下跌了",
        "经济和银行的投资",
        "今天股票上涨",
        "银行投资市场",
        "价格在市场上涨",
    ]
    return (animals + finance) * 6


class TestDictionarySegmenter:
    def test_segments_known_words(self):
        seg = DictionarySegmenter(LEXICON)
        assert seg.segment("我的猫喜欢吃鱼") == ["我", "的", "猫", "喜欢", "吃", "鱼"]
        assert seg.segment("股票价格上涨了") == ["股票", "价格", "上涨", "了"]

    def test_prefers_dictionary_words_over_chars(self):
        seg = DictionarySegmenter(LEXICON)
        toks = seg.segment("兔子吃米饭")
        assert "兔子" in toks and "米饭" in toks

    def test_unknown_chars_fall_back_to_singles(self):
        seg = DictionarySegmenter(LEXICON)
        toks = seg.segment("猫写字")  # 写/字 are OOV
        assert toks == ["猫", "写", "字"]

    def test_punctuation_splits_runs(self):
        seg = DictionarySegmenter(LEXICON)
        toks = seg.segment("猫喜欢鱼，狗喜欢肉。")
        assert "，" not in toks and "。" not in toks
        assert toks.count("喜欢") == 2

    def test_latin_runs_pass_through(self):
        seg = DictionarySegmenter(LEXICON)
        assert seg.segment("GPU和TPU") == ["GPU", "和", "TPU"]

    def test_from_word_list(self):
        seg = DictionarySegmenter.from_word_list(["深度", "学习"])
        assert seg.segment("深度学习") == ["深度", "学习"]


class TestCJKTokenizerFactory:
    def test_seam_contract(self):
        tf = CJKTokenizerFactory(LEXICON)
        tok = tf.create("我的猫喜欢吃鱼")
        assert tok.count_tokens() == 6
        assert tok.has_more_tokens()
        assert tok.next_token() == "我"

    def test_preprocessor_applied(self):
        from deeplearning4j_tpu.nlp.tokenization import TokenPreProcess

        class Tag(TokenPreProcess):
            def pre_process(self, t):
                return f"<{t}>"

        tf = CJKTokenizerFactory(LEXICON).set_token_pre_processor(Tag())
        assert tf.create("猫吃鱼").get_tokens() == ["<猫>", "<吃>", "<鱼>"]


class TestChineseWord2Vec:
    def test_cjk_corpus_trains_with_topic_structure(self):
        """Word2Vec over raw (unspaced) Chinese sentences via the CJK
        factory: animal words must cluster away from finance words —
        impossible unless the segmenter actually produced words."""
        w2v = Word2Vec(
            sentence_iterator=corpus(),
            tokenizer_factory=CJKTokenizerFactory(LEXICON),
            layer_size=24, window_size=3, min_word_frequency=2,
            negative_sample=5, learning_rate=0.05, epochs=4,
            batch_size=512, seed=11)
        w2v.fit()
        assert w2v.has_word("股票") and w2v.has_word("猫")
        # no whole-sentence tokens leaked into the vocab
        assert not w2v.has_word("我的猫喜欢吃鱼")
        in_topic = w2v.similarity("猫", "狗")
        cross = w2v.similarity("猫", "股票")
        assert in_topic > cross, f"{in_topic} <= {cross}"
        near = w2v.words_nearest("银行", top_n=5)
        finance = {"股票", "市场", "价格", "经济", "投资", "上涨", "下跌"}
        assert len(finance.intersection(near)) >= 2, near
