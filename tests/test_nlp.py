"""NLP stack tests.

Mirrors the reference suite (SURVEY.md §4 NLP row): Word2Vec
nearest-neighbor sanity (`Word2VecTests`-style: topically related words
end up close), serialization round-trips, tokenizer/iterator unit
tests, doc2vec + GloVe + TF-IDF behavior.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator,
    CollectionSentenceIterator,
    CommonPreprocessor,
    CountVectorizer,
    DefaultTokenizerFactory,
    Glove,
    LabelledDocument,
    NGramTokenizerFactory,
    ParagraphVectors,
    SequenceVectors,
    TfidfVectorizer,
    VocabConstructor,
    Word2Vec,
    WordVectorSerializer,
)
from deeplearning4j_tpu.nlp.vocab import build_huffman


def synthetic_corpus(n=400, seed=0):
    """Two-topic corpus: weather words co-occur, finance words co-occur."""
    rng = np.random.default_rng(seed)
    weather = ["rain", "snow", "storm", "cloud", "wind", "sun"]
    finance = ["bank", "money", "stock", "market", "trade", "price"]
    shared = ["the", "a", "and", "of", "in"]
    sentences = []
    for _ in range(n):
        topic = weather if rng.random() < 0.5 else finance
        words = [topic[rng.integers(len(topic))] for _ in range(8)]
        # sprinkle stopwords
        for i in sorted(rng.integers(0, len(words), 2))[::-1]:
            words.insert(i, shared[rng.integers(len(shared))])
        sentences.append(" ".join(words))
    return sentences


class TestTokenization:
    def test_default_tokenizer_and_preprocessor(self):
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        toks = fac.create("Hello, World! 42 times").get_tokens()
        assert toks == ["hello", "world", "time"] or toks == ["hello", "world", "times"]

    def test_ngram_tokenizer(self):
        fac = NGramTokenizerFactory(min_n=1, max_n=2)
        toks = fac.create("a b c").get_tokens()
        assert "a b" in toks and "b c" in toks and "a" in toks


class TestSentenceIterators:
    def test_collection_iterator_resets(self):
        it = CollectionSentenceIterator(["one", "two"])
        assert list(it) == ["one", "two"]
        assert list(it) == ["one", "two"]

    def test_line_iterator(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("first line\nsecond line\n")
        it = BasicLineIterator(p)
        assert list(it) == ["first line", "second line"]


class TestVocab:
    def test_construction_and_frequency_order(self):
        cache = VocabConstructor().build([["b", "a", "a"], ["a", "c"]])
        assert cache.num_words() == 3
        assert cache.word_at_index(0) == "a"  # most frequent first
        assert cache.word_frequency("a") == 3

    def test_min_frequency_pruning(self):
        cache = VocabConstructor(min_word_frequency=2).build(
            [["a", "a", "b"], ["c", "a"]])
        assert cache.contains_word("a") and not cache.contains_word("b")

    def test_huffman_codes_prefix_free(self):
        cache = VocabConstructor().build(
            [["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]])
        codes = {w: "".join(map(str, cache.word_for(w).codes))
                 for w in ["a", "b", "c", "d"]}
        # prefix-free and frequent words get shorter codes
        vals = list(codes.values())
        for i, c1 in enumerate(vals):
            for j, c2 in enumerate(vals):
                if i != j:
                    assert not c2.startswith(c1)
        assert len(codes["a"]) <= len(codes["d"])


class TestWord2Vec:
    @pytest.mark.parametrize("mode", ["sg_neg", "cbow", "hs", "cbow_hs"])
    def test_topic_clustering(self, mode):
        w2v = Word2Vec(
            sentence_iterator=synthetic_corpus(),
            layer_size=24, window_size=4, min_word_frequency=2,
            negative_sample=0 if mode in ("hs", "cbow_hs") else 5,
            use_hierarchic_softmax=mode in ("hs", "cbow_hs"),
            cbow=mode in ("cbow", "cbow_hs"),
            learning_rate=0.05, epochs=3, batch_size=512, seed=7)
        w2v.fit()
        # in-topic similarity must beat cross-topic similarity
        in_topic = w2v.similarity("rain", "snow")
        cross = w2v.similarity("rain", "money")
        assert in_topic > cross, f"{mode}: {in_topic} <= {cross}"
        near = w2v.words_nearest("stock", top_n=4)
        finance = {"bank", "money", "market", "trade", "price"}
        assert len(finance.intersection(near)) >= 2, near

    def test_word_vector_api(self):
        w2v = Word2Vec(sentence_iterator=["a b c", "a c"], layer_size=8,
                       epochs=1, min_word_frequency=1)
        w2v.fit()
        assert w2v.has_word("a") and not w2v.has_word("zzz")
        assert w2v.get_word_vector("a").shape == (8,)
        assert w2v.get_word_vector("zzz") is None


class TestSerialization:
    def _small_model(self):
        w2v = Word2Vec(sentence_iterator=["alpha beta gamma", "alpha gamma"],
                       layer_size=6, epochs=1)
        return w2v.fit()

    def test_binary_roundtrip(self, tmp_path):
        w2v = self._small_model()
        path = tmp_path / "vecs.bin"
        WordVectorSerializer.write_binary(w2v, path)
        loaded = WordVectorSerializer.read_binary(path)
        for w in ["alpha", "beta", "gamma"]:
            np.testing.assert_allclose(loaded.get_word_vector(w),
                                       w2v.get_word_vector(w), rtol=1e-6)

    def test_text_roundtrip(self, tmp_path):
        w2v = self._small_model()
        path = tmp_path / "vecs.txt"
        WordVectorSerializer.write_text(w2v, path)
        loaded = WordVectorSerializer.read_text(path)
        for w in ["alpha", "beta", "gamma"]:
            np.testing.assert_allclose(loaded.get_word_vector(w),
                                       w2v.get_word_vector(w), atol=1e-5)

    def test_warm_start_training_after_load(self, tmp_path):
        """A deserialized model (vocab + syn0 only) must be able to
        resume fit(): sampler/Huffman/output tables rebuild lazily
        instead of crashing, and trained vectors are kept (not reset)."""
        w2v = self._small_model()
        path = tmp_path / "vecs.bin"
        WordVectorSerializer.write_binary(w2v, path)
        loaded = WordVectorSerializer.read_binary(path)
        assert loaded._neg_table is None        # nothing built yet
        reinits = []
        orig_init = loaded._init_tables
        loaded._init_tables = lambda *a, **k: (reinits.append(1),
                                               orig_init(*a, **k))
        loaded.conf.epochs = 1
        loaded.fit([["alpha", "beta"], ["gamma", "alpha"]])
        assert not reinits, "warm start must not re-randomize syn0"
        assert loaded._neg_table is not None    # aux state rebuilt lazily
        assert loaded.syn1neg is not None
        assert np.isfinite(loaded.get_word_vector("alpha")).all()

    def test_warm_start_hs_actually_trains(self, tmp_path):
        """Deserialized vocabs carry no Huffman codes; HS warm-start
        must rebuild them (otherwise every update is masked to zero and
        fit() is a silent no-op)."""
        w2v = self._small_model()
        path = tmp_path / "vecs.bin"
        WordVectorSerializer.write_binary(w2v, path)
        loaded = WordVectorSerializer.read_binary(path)
        loaded.conf.use_hierarchic_softmax = True
        loaded.conf.negative = 0
        loaded.conf.epochs = 2
        loaded.fit([["alpha", "beta", "gamma"], ["gamma", "alpha"]])
        V = loaded.vocab.num_words()
        assert any(len(loaded.vocab.element_at_index(i).codes)
                   for i in range(V))
        # a masked no-op would leave the (zero-initialized) inner-node
        # table untouched; real HS updates write into syn1 immediately
        assert np.abs(np.asarray(loaded.syn1)).max() > 0, \
            "HS warm-start training changed nothing (masked no-op)"

    def test_warm_start_with_extra_rows_keeps_vectors(self, tmp_path):
        """ParagraphVectors-style warm start (extra label rows) must
        append rows, not re-randomize the loaded embedding table."""
        w2v = self._small_model()
        path = tmp_path / "vecs.bin"
        WordVectorSerializer.write_binary(w2v, path)
        loaded = WordVectorSerializer.read_binary(path)
        V = loaded.vocab.num_words()
        before = np.asarray(loaded.syn0).copy()
        # ~zero lr: any surviving difference would be re-randomization
        loaded.conf.learning_rate = 1e-9
        loaded.conf.min_learning_rate = 1e-12
        loaded.conf.epochs = 1
        loaded.fit([["alpha", "beta"]], extra_rows=2)
        assert loaded.syn0.shape[0] == V + 2
        np.testing.assert_allclose(np.asarray(loaded.syn0)[:V], before,
                                   atol=1e-5)


class TestParagraphVectors:
    def _docs(self):
        corpus = synthetic_corpus(200)
        return [LabelledDocument(s, [f"DOC_{i}"]) for i, s in enumerate(corpus)], corpus

    @pytest.mark.parametrize("dm", [False, True])
    def test_doc_vectors_cluster_by_topic(self, dm):
        docs, corpus = self._docs()
        pv = ParagraphVectors(documents=docs, layer_size=16, epochs=3,
                              min_word_frequency=2, dm=dm, seed=3)
        pv.fit()
        weather_docs = [i for i, s in enumerate(corpus) if "rain" in s and "bank" not in s]
        finance_docs = [i for i, s in enumerate(corpus) if "bank" in s and "rain" not in s]
        if len(weather_docs) >= 2 and len(finance_docs) >= 2:
            same = pv.similarity_doc(f"DOC_{weather_docs[0]}", f"DOC_{weather_docs[1]}")
            diff = pv.similarity_doc(f"DOC_{weather_docs[0]}", f"DOC_{finance_docs[0]}")
            assert same > diff

    def test_infer_vector(self):
        docs, _ = self._docs()
        pv = ParagraphVectors(documents=docs, layer_size=16, epochs=2,
                              min_word_frequency=2, seed=3)
        pv.fit()
        rows_before = pv.syn0.shape[0]
        vec = pv.infer_vector("rain snow storm wind")
        assert vec.shape == (16,)
        assert pv.syn0.shape[0] == rows_before  # scratch row popped
        # inferred weather doc is closer to weather words than finance words
        def cos(a, b):
            return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        weather_sim = cos(vec, pv.get_word_vector("rain"))
        finance_sim = cos(vec, pv.get_word_vector("bank"))
        assert weather_sim > finance_sim
        # inference must not mutate the trained model (frozen tables)
        syn1neg_before = pv.syn1neg.copy()
        pv.infer_vector("rain snow storm wind")
        np.testing.assert_array_equal(syn1neg_before, np.asarray(pv.syn1neg))

    def test_duplicate_labels_share_one_row(self):
        docs = [LabelledDocument("rain snow storm", ["weather"]),
                LabelledDocument("wind cloud sun rain", ["weather"]),
                LabelledDocument("bank money stock", ["finance"])]
        pv = ParagraphVectors(documents=docs, layer_size=8, epochs=2,
                              min_word_frequency=1, seed=1)
        pv.fit()
        assert pv.labels == ["weather", "finance"]
        assert pv.syn0.shape[0] == pv.vocab.num_words() + 2
        assert np.isnan(pv.similarity_doc("weather", "nope"))


class TestGlove:
    def test_topic_clustering(self):
        g = Glove(layer_size=16, window=4, min_word_frequency=2,
                  epochs=20, learning_rate=0.05, seed=5)
        seqs = [s.split() for s in synthetic_corpus(300)]
        g.fit(seqs)
        assert g.similarity("rain", "snow") > g.similarity("rain", "money")


class TestBagOfWords:
    def test_count_vectorizer(self):
        cv = CountVectorizer()
        X = cv.fit_transform(["a b a", "b c"])
        assert X.shape == (2, 3)
        assert X[0, cv.vocab.index_of("a")] == 2

    def test_tfidf_downweights_common_terms(self):
        tv = TfidfVectorizer()
        X = tv.fit_transform(["common rare1", "common rare2", "common rare3"])
        ci = tv.vocab.index_of("common")
        ri = tv.vocab.index_of("rare1")
        assert X[0, ci] < X[0, ri]  # idf(common)=log(1)=0


class TestCnnSentenceIterator:
    def test_batch_shapes_and_mask(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        w2v = Word2Vec(sentence_iterator=["deep learning rocks",
                                          "learning is fun"],
                       layer_size=4, epochs=1)
        w2v.fit()
        it = CnnSentenceDataSetIterator(
            ["deep learning", "fun"], [0, 1], w2v, num_classes=2, batch_size=2)
        ds = next(iter(it))
        assert ds.features.shape[0] == 2
        assert ds.features.shape[3] == 1
        assert ds.labels.shape == (2, 2)
        assert ds.features_mask[1].sum() == 1  # "fun" → one token


class TestDistributedSequenceVectors:
    """Mesh-sharded embedding training (the dl4j-spark-nlp distributed
    Word2Vec capability): pair batches shard over the data axis, tables
    replicate, XLA inserts the grad all-reduce. Global-view jit
    semantics mean the sharded run must match the single-device run."""

    def _corpus(self):
        rng = np.random.default_rng(11)
        vocab = [f"w{i}" for i in range(50)]
        return [[vocab[t] for t in rng.integers(0, 50, 60)]
                for _ in range(30)]

    def test_mesh_matches_single_device(self):
        import jax
        from jax.sharding import Mesh

        seqs = self._corpus()
        kw = dict(layer_size=16, window_size=3, negative_sample=3,
                  min_word_frequency=1, epochs=2, batch_size=64, seed=5)
        single = Word2Vec(**kw)
        single.build_vocab(seqs)
        single.fit(seqs)

        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        sharded = Word2Vec(**kw)
        sharded.mesh = mesh
        sharded.build_vocab(seqs)
        sharded.fit(seqs)

        np.testing.assert_allclose(sharded.syn0, single.syn0,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(sharded.syn1neg, single.syn1neg,
                                   rtol=1e-4, atol=1e-5)

    def test_fused_flush_trains(self):
        # steps_per_flush>1 path must still learn co-occurrence structure
        rng = np.random.default_rng(3)
        seqs = []
        for _ in range(120):
            s = []
            for _ in range(30):
                s.extend(["sun", "moon"] if rng.random() < 0.5
                         else ["cat", "dog"])
            seqs.append(s)
        w2v = Word2Vec(layer_size=24, window_size=2, negative_sample=4,
                       epochs=3, batch_size=256, seed=1)
        w2v.conf.steps_per_flush = 4
        w2v.build_vocab(seqs)
        w2v.fit(seqs)
        assert w2v.similarity("sun", "moon") > w2v.similarity("sun", "dog")


class TestSparseUpdateParity:
    """The closed-form scatter update in _sg_neg_math must equal the
    dense autodiff gradient of the SGNS loss (with per-row count
    normalization) — the sparse path exists for memory, not for
    different math."""

    def test_matches_autodiff_dense(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _row_counts, _sg_neg_math)

        rng = np.random.default_rng(0)
        V, D, B, K = 40, 8, 16, 3
        syn0 = jnp.asarray(rng.standard_normal((V, D)), jnp.float32)
        syn1 = jnp.asarray(rng.standard_normal((V, D)), jnp.float32)
        centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
        contexts = jnp.asarray(rng.integers(0, V, B), jnp.int32)
        negs = jnp.asarray(rng.integers(0, V, (B, K)), jnp.int32)
        lr = jnp.float32(0.05)

        def loss_fn(s0, s1):
            v = jnp.take(s0, centers, axis=0)
            u_pos = jnp.take(s1, contexts, axis=0)
            u_neg = jnp.take(s1, negs, axis=0)
            pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, axis=-1))
            neg = jnp.sum(jax.nn.log_sigmoid(
                -jnp.einsum("bd,bkd->bk", v, u_neg)), axis=-1)
            return -jnp.sum(pos + neg)

        loss_ref, (g0, g1) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(syn0, syn1)
        want0 = syn0 - lr * g0 / _row_counts(V, centers)
        want1 = syn1 - lr * g1 / _row_counts(V, contexts, negs)

        got0, got1, loss = _sg_neg_math(syn0, syn1, centers, contexts,
                                        negs, lr, 0)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-5, atol=1e-6)
        assert abs(float(loss) * B - float(loss_ref)) < 1e-3

    def test_inference_mode_freezes_rows(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp.sequencevectors import _sg_neg_math

        rng = np.random.default_rng(1)
        V, D = 10, 4
        syn0 = jnp.asarray(rng.standard_normal((V, D)), jnp.float32)
        syn1 = jnp.asarray(rng.standard_normal((V, D)), jnp.float32)
        centers = jnp.asarray([2, 8, 9], jnp.int32)   # 2 frozen, 8/9 live
        contexts = jnp.asarray([1, 3, 4], jnp.int32)
        negs = jnp.asarray([[5], [6], [7]], jnp.int32)
        got0, got1, _ = _sg_neg_math(syn0, syn1, centers, contexts, negs,
                                     jnp.float32(0.1), 8)
        np.testing.assert_array_equal(np.asarray(got1), np.asarray(syn1))
        np.testing.assert_array_equal(np.asarray(got0[:8]),
                                      np.asarray(syn0[:8]))
        assert not np.allclose(np.asarray(got0[8:]), np.asarray(syn0[8:]))


class TestSparseCbowHsParity:
    """CBOW-NS / SG-HS / CBOW-HS closed-form scatters vs autodiff."""

    def _setup(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(2)
        V, D, B, W2, K, C = 30, 6, 10, 4, 3, 5
        return (rng, V, D, B, W2, K, C,
                jnp.asarray(rng.standard_normal((V, D)), jnp.float32),
                jnp.asarray(rng.standard_normal((V, D)), jnp.float32))

    def test_cbow_neg_matches_autodiff(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _cbow_neg_step, _row_counts)

        rng, V, D, B, W2, K, C, syn0, syn1 = self._setup()
        ctx = jnp.asarray(rng.integers(0, V, (B, W2)), jnp.int32)
        mask = jnp.asarray(rng.random((B, W2)) < 0.8, jnp.float32)
        mask = mask.at[:, 0].set(1.0)
        centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
        negs = jnp.asarray(rng.integers(0, V, (B, K)), jnp.int32)
        lr = jnp.float32(0.07)

        def loss_fn(s0, s1):
            vecs = jnp.take(s0, ctx, axis=0)
            m = mask[..., None]
            h = jnp.sum(vecs * m, axis=1) / jnp.clip(
                jnp.sum(mask, axis=1, keepdims=True), 1.0, None)
            u_pos = jnp.take(s1, centers, axis=0)
            u_neg = jnp.take(s1, negs, axis=0)
            pos = jax.nn.log_sigmoid(jnp.sum(h * u_pos, axis=-1))
            neg = jnp.sum(jax.nn.log_sigmoid(
                -jnp.einsum("bd,bkd->bk", h, u_neg)), axis=-1)
            return -jnp.sum(pos + neg)

        g0, g1 = jax.grad(loss_fn, argnums=(0, 1))(syn0, syn1)
        want0 = syn0 - lr * g0 / _row_counts(V, (ctx, mask))
        want1 = syn1 - lr * g1 / _row_counts(V, centers, negs)
        got0, got1, _ = _cbow_neg_step(syn0, syn1, ctx, mask, centers,
                                       negs, lr, 0)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-5, atol=1e-6)

    def test_sg_hs_matches_autodiff(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _row_counts, _sg_hs_step)

        rng, V, D, B, W2, K, C, syn0, syn1 = self._setup()
        centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
        points = jnp.asarray(rng.integers(0, V, (B, C)), jnp.int32)
        codes = jnp.asarray(rng.integers(0, 2, (B, C)), jnp.float32)
        cmask = jnp.asarray(rng.random((B, C)) < 0.7, jnp.float32)
        cmask = cmask.at[:, 0].set(1.0)
        lr = jnp.float32(0.05)

        def loss_fn(s0, s1):
            v = jnp.take(s0, centers, axis=0)
            u = jnp.take(s1, points, axis=0)
            sign = 1.0 - 2.0 * codes
            logits = jnp.einsum("bd,bcd->bc", v, u) * sign
            return -jnp.sum(jax.nn.log_sigmoid(logits) * cmask)

        g0, g1 = jax.grad(loss_fn, argnums=(0, 1))(syn0, syn1)
        want0 = syn0 - lr * g0 / _row_counts(V, centers)
        want1 = syn1 - lr * g1 / _row_counts(V, (points, cmask))
        got0, got1, _ = _sg_hs_step(syn0, syn1, centers, points, codes,
                                    cmask, lr)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-5, atol=1e-6)

    def test_cbow_hs_matches_autodiff(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _cbow_hs_step, _row_counts)

        rng, V, D, B, W2, K, C, syn0, syn1 = self._setup()
        ctx = jnp.asarray(rng.integers(0, V, (B, W2)), jnp.int32)
        mask = jnp.asarray(rng.random((B, W2)) < 0.8, jnp.float32)
        mask = mask.at[:, 0].set(1.0)
        centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
        points = jnp.asarray(rng.integers(0, V, (B, C)), jnp.int32)
        codes = jnp.asarray(rng.integers(0, 2, (B, C)), jnp.float32)
        cmask = jnp.asarray(rng.random((B, C)) < 0.7, jnp.float32)
        cmask = cmask.at[:, 0].set(1.0)
        lr = jnp.float32(0.05)

        def loss_fn(s0, s1):
            vecs = jnp.take(s0, ctx, axis=0)
            m = mask[..., None]
            h = jnp.sum(vecs * m, axis=1) / jnp.clip(
                jnp.sum(mask, axis=1, keepdims=True), 1.0, None)
            u = jnp.take(s1, points, axis=0)
            sign = 1.0 - 2.0 * codes
            logits = jnp.einsum("bd,bcd->bc", h, u) * sign
            return -jnp.sum(jax.nn.log_sigmoid(logits) * cmask)

        g0, g1 = jax.grad(loss_fn, argnums=(0, 1))(syn0, syn1)
        want0 = syn0 - lr * g0 / _row_counts(V, (ctx, mask))
        want1 = syn1 - lr * g1 / _row_counts(V, (points, cmask))
        got0, got1, _ = _cbow_hs_step(syn0, syn1, ctx, mask, centers,
                                      points, codes, cmask, lr)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-5, atol=1e-6)


class TestMaskedTailParity:
    """Padded-tail flushes must equal their ragged-shape equivalents:
    the epoch-end tail runs padded to the compiled [B] shape with a
    validity mask (one XLA compile for every tail length) and the masked
    math must change nothing numerically."""

    def _tables(self, rng, V, D):
        # numpy (not device arrays): the jitted steps donate their table
        # args, so each call must receive a fresh host->device copy
        syn0 = (rng.standard_normal((V, D)) * 0.1).astype(np.float32)
        syn1 = (rng.standard_normal((V, D)) * 0.1).astype(np.float32)
        return syn0, syn1

    def test_sg_neg_masked_equals_ragged(self):
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _sg_neg_step, _sg_neg_step_masked)

        rng = np.random.default_rng(0)
        V, D, B, n, K = 50, 8, 16, 11, 5
        syn0, syn1 = self._tables(rng, V, D)
        centers = rng.integers(0, V, n).astype(np.int32)
        contexts = rng.integers(0, V, n).astype(np.int32)
        negs = rng.integers(0, V, (n, K)).astype(np.int32)
        lr = np.float32(0.05)

        want0, want1, wloss = _sg_neg_step(syn0, syn1, centers, contexts,
                                           negs, lr, 0)
        pc = np.zeros(B, np.int32); pc[:n] = centers
        px = np.zeros(B, np.int32); px[:n] = contexts
        pn = np.zeros((B, K), np.int32); pn[:n] = negs
        valid = np.zeros(B, np.float32); valid[:n] = 1.0
        got0, got1, gloss = _sg_neg_step_masked(syn0, syn1, pc, px, pn,
                                                lr, 0, valid)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(float(gloss), float(wloss), rtol=1e-5)

    def test_sg_hs_masked_equals_ragged(self):
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _sg_hs_step, _sg_hs_step_masked)

        rng = np.random.default_rng(1)
        V, D, B, n, C = 50, 8, 16, 9, 6
        syn0, syn1 = self._tables(rng, V, D)
        centers = rng.integers(0, V, n).astype(np.int32)
        points = rng.integers(0, V, (n, C)).astype(np.int32)
        codes = rng.integers(0, 2, (n, C)).astype(np.float32)
        cmask = (rng.random((n, C)) < 0.7).astype(np.float32)
        cmask[:, 0] = 1.0
        lr = np.float32(0.05)

        want0, want1, wloss = _sg_hs_step(syn0, syn1, centers, points,
                                          codes, cmask, lr)
        pc = np.zeros(B, np.int32); pc[:n] = centers
        pp = np.zeros((B, C), np.int32); pp[:n] = points
        pcd = np.zeros((B, C), np.float32); pcd[:n] = codes
        pm = np.zeros((B, C), np.float32); pm[:n] = cmask
        valid = np.zeros(B, np.float32); valid[:n] = 1.0
        got0, got1, gloss = _sg_hs_step_masked(syn0, syn1, pc, pp, pcd,
                                               pm, lr, valid)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(float(gloss), float(wloss), rtol=1e-5)

    def test_fit_compile_count_stable_across_refits(self):
        """Refits over the same corpus draw different reduced windows,
        so epoch-end tail lengths differ run to run — the padded-tail
        path must absorb that with NO new XLA compile. Asserted via the
        jit cache sizes of every flush step the skip-gram path uses."""
        from deeplearning4j_tpu.nlp import sequencevectors as sv
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        rng = np.random.default_rng(2)
        seqs = [[f"w{t}" for t in rng.integers(0, 40, 60)]
                for _ in range(12)]
        w2v = Word2Vec(layer_size=16, window_size=3, negative_sample=4,
                       min_word_frequency=1, epochs=2, batch_size=128)
        w2v.build_vocab(seqs)
        w2v.fit(seqs)                  # warmup: compiles every shape once
        steps = (sv._sg_neg_step, sv._sg_neg_step_masked, sv._sg_neg_multi)
        sizes = [f._cache_size() for f in steps]
        for _ in range(3):             # tail length varies per refit
            w2v._init_tables()
            w2v.fit(seqs)
        assert [f._cache_size() for f in steps] == sizes, \
            "refit with a different tail length triggered a recompile"
        assert np.isfinite(w2v.get_word_vector("w1")).all()

    def test_cbow_neg_masked_equals_ragged(self):
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _cbow_neg_step, _cbow_neg_step_masked)

        rng = np.random.default_rng(3)
        V, D, B, n, K, W2 = 50, 8, 16, 10, 5, 6
        syn0, syn1 = self._tables(rng, V, D)
        ctx = rng.integers(0, V, (n, W2)).astype(np.int32)
        mask = (rng.random((n, W2)) < 0.8).astype(np.float32)
        mask[:, 0] = 1.0
        centers = rng.integers(0, V, n).astype(np.int32)
        negs = rng.integers(0, V, (n, K)).astype(np.int32)
        lr = np.float32(0.05)

        want0, want1, wloss = _cbow_neg_step(syn0, syn1, ctx, mask,
                                             centers, negs, lr, 0)
        pctx = np.zeros((B, W2), np.int32); pctx[:n] = ctx
        pmask = np.zeros((B, W2), np.float32); pmask[:n] = mask
        pc = np.zeros(B, np.int32); pc[:n] = centers
        pn = np.zeros((B, K), np.int32); pn[:n] = negs
        valid = np.zeros(B, np.float32); valid[:n] = 1.0
        got0, got1, gloss = _cbow_neg_step_masked(
            syn0, syn1, pctx, pmask, pc, pn, lr, 0, valid)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(float(gloss), float(wloss), rtol=1e-5)

    def test_cbow_hs_masked_equals_ragged(self):
        from deeplearning4j_tpu.nlp.sequencevectors import (
            _cbow_hs_step, _cbow_hs_step_masked)

        rng = np.random.default_rng(4)
        V, D, B, n, C, W2 = 50, 8, 16, 7, 6, 6
        syn0, syn1 = self._tables(rng, V, D)
        ctx = rng.integers(0, V, (n, W2)).astype(np.int32)
        mask = (rng.random((n, W2)) < 0.8).astype(np.float32)
        mask[:, 0] = 1.0
        centers = rng.integers(0, V, n).astype(np.int32)
        points = rng.integers(0, V, (n, C)).astype(np.int32)
        codes = rng.integers(0, 2, (n, C)).astype(np.float32)
        cmask = (rng.random((n, C)) < 0.7).astype(np.float32)
        cmask[:, 0] = 1.0
        lr = np.float32(0.05)

        want0, want1, wloss = _cbow_hs_step(syn0, syn1, ctx, mask, centers,
                                            points, codes, cmask, lr)
        pctx = np.zeros((B, W2), np.int32); pctx[:n] = ctx
        pmask = np.zeros((B, W2), np.float32); pmask[:n] = mask
        pc = np.zeros(B, np.int32); pc[:n] = centers
        pp = np.zeros((B, C), np.int32); pp[:n] = points
        pcd = np.zeros((B, C), np.float32); pcd[:n] = codes
        pcm = np.zeros((B, C), np.float32); pcm[:n] = cmask
        valid = np.zeros(B, np.float32); valid[:n] = 1.0
        got0, got1, gloss = _cbow_hs_step_masked(
            syn0, syn1, pctx, pmask, pc, pp, pcd, pcm, lr, valid)
        np.testing.assert_allclose(np.asarray(got0), np.asarray(want0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want1),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(float(gloss), float(wloss), rtol=1e-5)
