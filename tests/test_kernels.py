"""Pallas kernel parity tests (the reference's accelerated-path
validation pattern: `CuDNNGradientChecks`, `ValidateCudnnLSTM` — helper
vs built-in on identical inputs). Interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.kernels import flash_attention
from deeplearning4j_tpu.kernels.flash_attention import _xla_attention


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (2, 64, 2, 16)) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("blocks", [(32, 32), (16, 64), (64, 16)])
    def test_forward_parity(self, qkv, causal, blocks):
        q, k, v = qkv
        bq, bk = blocks
        got = flash_attention(q, k, v, causal, bq, bk, True)
        want = _xla_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("T", [40, 100, 129])
    def test_ragged_tail_blocks(self, T, causal):
        # T not divisible by 32 → padded tail block must not corrupt
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 2, 8)) for kk in ks)
        got = flash_attention(q, k, v, causal, 32, 32, True)
        want = _xla_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_backward_parity(self):
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (1, 40, 2, 8)) for kk in ks)

        def loss_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, True, 32, 32, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_layer_flash_path_ragged_seq_grad(self):
        # MultiHeadAttention routed through the flash path at T=40:
        # forward parity AND gradient check vs the XLA path.
        from deeplearning4j_tpu.nn.layers import MultiHeadAttention
        layer_flash = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                         causal=True, use_flash=True)
        layer_xla = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                       causal=True, use_flash=False)
        params = layer_flash.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 40, 8))
        y1, _ = layer_flash.forward(params, {}, x)
        y2, _ = layer_xla.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-4, atol=1e-5)

        def loss(layer):
            def f(p):
                y, _ = layer.forward(p, {}, x)
                return jnp.sum(y ** 2)
            return f

        g1 = jax.grad(loss(layer_flash))(params)
        g2 = jax.grad(loss(layer_xla))(params)
        for name in g1:
            # atol=5e-5, not 1e-5: the Pallas flash backward accumulates
            # blockwise (different order than the XLA vjp), so grads that
            # are analytic zeros by softmax shift-invariance (bk here,
            # magnitude ~1e-6 against W-grads of ~1e2) sit at the fp32
            # cancellation noise floor rather than matching bitwise
            np.testing.assert_allclose(np.asarray(g1[name]),
                                       np.asarray(g2[name]),
                                       rtol=1e-4, atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("blocks", [(32, 32), (16, 64), (64, 16)])
    def test_backward_parity(self, qkv, causal, blocks):
        # exercises BOTH Pallas backward kernels (dq and dk/dv) against
        # the XLA vjp across unequal block sizes — q-time and k-time are
        # padded independently per kernel (T=64 with bq=16/bk=64 pads
        # each axis to its own block multiple)
        q, k, v = qkv
        bq, bk = blocks

        def loss_flash(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, causal, bq, bk, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, causal) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_no_padding_blowup_between_default_blocks(self):
        # q-time and k-time pad INDEPENDENTLY (to a bq / bk multiple),
        # so T strictly between the default block sizes can never
        # balloon the buffers (an earlier joint-lcm padding scheme
        # blew T=600 up to 38400)
        from deeplearning4j_tpu.kernels.flash_attention import _ceil_to
        for T in (600, 513, 1000, 1500):
            bq = min(512, T)
            bk = min(1024, T)
            assert _ceil_to(T, bq) < 2 * T
            assert _ceil_to(T, bk) < 2 * T

    def test_default_blocks_between_window_parity(self):
        # T=600 runs through the coerced-block path end to end
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q, k, v = (jax.random.normal(kk, (1, 600, 1, 8)) for kk in ks)
        got = flash_attention(q, k, v, True)
        want = _xla_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("T", [100, 129])
    def test_backward_ragged_tails(self, T):
        # ragged T through the backward's lcm padding: padded queries and
        # keys must contribute exactly zero to every gradient
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 2, 8)) for kk in ks)

        def loss_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, True, 32, 32, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=2e-5)

    def test_layer_flash_path_matches_xla_path(self):
        from deeplearning4j_tpu.nn.layers import MultiHeadAttention
        layer_flash = MultiHeadAttention(n_in=16, n_out=16, n_heads=2,
                                         causal=True, use_flash=True)
        layer_xla = MultiHeadAttention(n_in=16, n_out=16, n_heads=2,
                                       causal=True, use_flash=False)
        params = layer_flash.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        y1, _ = layer_flash.forward(params, {}, x)
        y2, _ = layer_xla.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-4, atol=1e-5)


class TestFlashFallbackSeam:
    """The helper seam degrades like the reference's cuDNN fallback
    (`ConvolutionLayer.java:76-80`): auto mode probes the kernel
    eagerly once per backend — a probe failure routes attention through
    the XLA path with one warning — while an explicit use_flash=True
    surfaces the real kernel error."""

    def _layer(self, use_flash):
        import jax
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention

        layer = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                   use_flash=use_flash)
        layer.set_n_in(InputType.recurrent(8))
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 8))
        return layer, params, x

    def test_auto_mode_probe_failure_falls_back(self, monkeypatch):
        import numpy as np
        import jax
        import deeplearning4j_tpu.kernels as kmod
        from deeplearning4j_tpu.nn.layers import attention as attn_mod

        # true XLA reference first (no patches)
        layer, params, x = self._layer(False)
        want = np.asarray(layer.forward(params, {}, x)[0])

        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(kmod, "flash_attention", boom)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        attn_mod._FLASH_OK.clear()
        layer, params, x = self._layer(None)       # auto
        got = np.asarray(layer.forward(params, {}, x)[0])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert attn_mod._FLASH_OK.get("tpu") is False
        attn_mod._FLASH_OK.clear()                 # don't poison later tests

    def test_forced_flash_failure_surfaces(self, monkeypatch):
        import pytest
        import deeplearning4j_tpu.kernels as kmod

        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(kmod, "flash_attention", boom)
        layer, params, x = self._layer(True)
        with pytest.raises(RuntimeError, match="kernel exploded"):
            layer.forward(params, {}, x)
