"""Pallas kernel parity tests (the reference's accelerated-path
validation pattern: `CuDNNGradientChecks`, `ValidateCudnnLSTM` — helper
vs built-in on identical inputs). Interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.kernels import flash_attention
from deeplearning4j_tpu.kernels.flash_attention import _xla_attention


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (2, 64, 2, 16)) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("blocks", [(32, 32), (16, 64), (64, 16)])
    def test_forward_parity(self, qkv, causal, blocks):
        q, k, v = qkv
        bq, bk = blocks
        got = flash_attention(q, k, v, causal, bq, bk, True)
        want = _xla_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("T", [40, 100, 129])
    def test_ragged_tail_blocks(self, T, causal):
        # T not divisible by 32 → padded tail block must not corrupt
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 2, 8)) for kk in ks)
        got = flash_attention(q, k, v, causal, 32, 32, True)
        want = _xla_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_backward_parity(self):
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (1, 40, 2, 8)) for kk in ks)

        def loss_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, True, 32, 32, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_layer_flash_path_ragged_seq_grad(self):
        # MultiHeadAttention routed through the flash path at T=40:
        # forward parity AND gradient check vs the XLA path.
        from deeplearning4j_tpu.nn.layers import MultiHeadAttention
        layer_flash = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                         causal=True, use_flash=True)
        layer_xla = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                       causal=True, use_flash=False)
        params = layer_flash.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 40, 8))
        y1, _ = layer_flash.forward(params, {}, x)
        y2, _ = layer_xla.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-4, atol=1e-5)

        def loss(layer):
            def f(p):
                y, _ = layer.forward(p, {}, x)
                return jnp.sum(y ** 2)
            return f

        g1 = jax.grad(loss(layer_flash))(params)
        g2 = jax.grad(loss(layer_xla))(params)
        for name in g1:
            # atol=5e-5, not 1e-5: the Pallas flash backward accumulates
            # blockwise (different order than the XLA vjp), so grads that
            # are analytic zeros by softmax shift-invariance (bk here,
            # magnitude ~1e-6 against W-grads of ~1e2) sit at the fp32
            # cancellation noise floor rather than matching bitwise
            np.testing.assert_allclose(np.asarray(g1[name]),
                                       np.asarray(g2[name]),
                                       rtol=1e-4, atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("blocks", [(32, 32), (16, 64), (64, 16)])
    def test_backward_parity(self, qkv, causal, blocks):
        # exercises BOTH Pallas backward kernels (dq and dk/dv) against
        # the XLA vjp across unequal block sizes — q-time and k-time are
        # padded independently per kernel (T=64 with bq=16/bk=64 pads
        # each axis to its own block multiple)
        q, k, v = qkv
        bq, bk = blocks

        def loss_flash(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, causal, bq, bk, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, causal) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_no_padding_blowup_between_default_blocks(self):
        # q-time and k-time pad INDEPENDENTLY (to a bq / bk multiple),
        # so T strictly between the default block sizes can never
        # balloon the buffers (an earlier joint-lcm padding scheme
        # blew T=600 up to 38400)
        from deeplearning4j_tpu.kernels.flash_attention import _ceil_to
        for T in (600, 513, 1000, 1500):
            bq = min(512, T)
            bk = min(1024, T)
            assert _ceil_to(T, bq) < 2 * T
            assert _ceil_to(T, bk) < 2 * T

    def test_default_blocks_between_window_parity(self):
        # T=600 runs through the coerced-block path end to end
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q, k, v = (jax.random.normal(kk, (1, 600, 1, 8)) for kk in ks)
        got = flash_attention(q, k, v, True)
        want = _xla_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("T", [100, 129])
    def test_backward_ragged_tails(self, T):
        # ragged T through the backward's lcm padding: padded queries and
        # keys must contribute exactly zero to every gradient
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 2, 8)) for kk in ks)

        def loss_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, True, 32, 32, True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_xla_attention(q_, k_, v_, True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=2e-5)

    def test_layer_flash_path_matches_xla_path(self):
        from deeplearning4j_tpu.nn.layers import MultiHeadAttention
        layer_flash = MultiHeadAttention(n_in=16, n_out=16, n_heads=2,
                                         causal=True, use_flash=True)
        layer_xla = MultiHeadAttention(n_in=16, n_out=16, n_heads=2,
                                       causal=True, use_flash=False)
        params = layer_flash.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        y1, _ = layer_flash.forward(params, {}, x)
        y2, _ = layer_xla.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-4, atol=1e-5)


class TestFlashFallbackSeam:
    """The helper seam degrades like the reference's cuDNN fallback
    (`ConvolutionLayer.java:76-80`): auto mode probes the kernel
    eagerly once per backend — a probe failure routes attention through
    the XLA path with one warning — while an explicit use_flash=True
    surfaces the real kernel error."""

    def _layer(self, use_flash):
        import jax
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention

        layer = MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                   use_flash=use_flash)
        layer.set_n_in(InputType.recurrent(8))
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 8))
        return layer, params, x

    def test_auto_mode_probe_failure_falls_back(self, monkeypatch):
        import numpy as np
        import jax
        import deeplearning4j_tpu.kernels as kmod
        from deeplearning4j_tpu.nn.layers import attention as attn_mod

        # true XLA reference first (no patches)
        layer, params, x = self._layer(False)
        want = np.asarray(layer.forward(params, {}, x)[0])

        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(kmod, "flash_attention", boom)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        attn_mod._FLASH_OK.clear()
        layer, params, x = self._layer(None)       # auto
        got = np.asarray(layer.forward(params, {}, x)[0])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert attn_mod._FLASH_OK.get("tpu") is False
        attn_mod._FLASH_OK.clear()                 # don't poison later tests

    def test_forced_flash_failure_surfaces(self, monkeypatch):
        import pytest
        import deeplearning4j_tpu.kernels as kmod

        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(kmod, "flash_attention", boom)
        layer, params, x = self._layer(True)
        with pytest.raises(RuntimeError, match="kernel exploded"):
            layer.forward(params, {}, x)


class TestKernelGate:
    """`kernels_enabled()` — the DL4J_PALLAS_KERNELS switch: off/on
    spellings, TPU-only default, typo'd values loud."""

    def test_env_spellings(self, monkeypatch):
        from deeplearning4j_tpu.kernels import kernels_enabled
        for v in ("0", "off", "false", "no"):
            monkeypatch.setenv("DL4J_PALLAS_KERNELS", v)
            assert kernels_enabled() is False
        for v in ("1", "on", "true", "yes"):
            monkeypatch.setenv("DL4J_PALLAS_KERNELS", v)
            assert kernels_enabled() is True
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "maybe")
        with pytest.raises(ValueError):
            kernels_enabled()

    def test_default_is_backend_gated(self, monkeypatch):
        from deeplearning4j_tpu.kernels import kernels_enabled
        monkeypatch.delenv("DL4J_PALLAS_KERNELS", raising=False)
        assert kernels_enabled() is (jax.default_backend() == "tpu")


class TestLayerNormKernel:
    """Fused LayerNorm(+residual) vs the jnp reference
    (`layer_norm_reference`) — interpret mode on CPU."""

    def _data(self, D=24, dtype=jnp.float32):
        k = jax.random.PRNGKey(0)
        x = jax.random.normal(k, (2, 40, D), dtype)
        g = (jax.random.normal(jax.random.fold_in(k, 1), (D,), dtype)
             + jnp.asarray(1.0, dtype))
        b = jax.random.normal(jax.random.fold_in(k, 2), (D,), dtype)
        return x, g, b

    def test_forward_parity(self):
        from deeplearning4j_tpu.kernels.layernorm import layer_norm
        from deeplearning4j_tpu.nn.layers.normalization import (
            layer_norm_reference)
        x, g, b = self._data()
        got = layer_norm(x, g, b, 1e-5, 256, True)
        want = layer_norm_reference(x, g, b, 1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("R", [3, 8, 130])  # ragged row padding
    def test_ragged_rows(self, R):
        from deeplearning4j_tpu.kernels.layernorm import layer_norm
        from deeplearning4j_tpu.nn.layers.normalization import (
            layer_norm_reference)
        k = jax.random.PRNGKey(3)
        x = jax.random.normal(k, (R, 16))
        g = jnp.ones((16,))
        b = jnp.zeros((16,))
        got = layer_norm(x, g, b, 1e-5, 64, True)
        want = layer_norm_reference(x, g, b, 1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_backward_parity(self):
        from deeplearning4j_tpu.kernels.layernorm import layer_norm
        from deeplearning4j_tpu.nn.layers.normalization import (
            layer_norm_reference)
        x, g, b = self._data()

        def lk(x_, g_, b_):
            return jnp.sum(layer_norm(x_, g_, b_, 1e-5, 256, True) ** 2)

        def lr(x_, g_, b_):
            return jnp.sum(layer_norm_reference(x_, g_, b_, 1e-5) ** 2)

        ga = jax.grad(lk, argnums=(0, 1, 2))(x, g, b)
        gb = jax.grad(lr, argnums=(0, 1, 2))(x, g, b)
        for a, c in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=2e-4, atol=2e-5)

    def test_residual_fusion_parity(self):
        from deeplearning4j_tpu.kernels.layernorm import (
            residual_layer_norm)
        from deeplearning4j_tpu.nn.layers.normalization import (
            layer_norm_reference)
        x, g, b = self._data()
        h = jax.random.normal(jax.random.PRNGKey(9), x.shape)
        s, y = residual_layer_norm(x, h, g, b, 1e-5, 256, True)
        np.testing.assert_allclose(np.asarray(s), np.asarray(x + h),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(layer_norm_reference(x + h, g, b,
                                                           1e-5)),
            rtol=1e-6, atol=1e-6)

        def lk(x_, h_):
            s_, y_ = residual_layer_norm(x_, h_, g, b, 1e-5, 256, True)
            return jnp.sum(y_ ** 2) + jnp.sum(s_ ** 3)

        def lr(x_, h_):
            s_ = x_ + h_
            return (jnp.sum(layer_norm_reference(s_, g, b, 1e-5) ** 2)
                    + jnp.sum(s_ ** 3))

        ga = jax.grad(lk, argnums=(0, 1))(x, h)
        gb = jax.grad(lr, argnums=(0, 1))(x, h)
        for a, c in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=2e-4, atol=2e-5)

    def test_bf16_activations(self):
        # mixed_bf16 policy: bf16 in/out, fp32 row statistics inside
        from deeplearning4j_tpu.kernels.layernorm import layer_norm
        from deeplearning4j_tpu.nn.layers.normalization import (
            layer_norm_reference)
        x, g, b = self._data(dtype=jnp.bfloat16)
        got = layer_norm(x, g, b, 1e-5, 256, True)
        assert got.dtype == jnp.bfloat16
        want = layer_norm_reference(x, g, b, 1e-5)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_layer_dispatch_identical_on_off(self, monkeypatch):
        # the DL4J_PALLAS_KERNELS=0 fallback and the kernel path must
        # agree through the LayerNormalization layer API
        from deeplearning4j_tpu.nn.layers.normalization import (
            LayerNormalization)
        layer = LayerNormalization(n_out=16)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 12, 16))
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "0")
        off, _ = layer.forward(params, {}, x)
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "1")
        on, _ = layer.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(on), np.asarray(off),
                                   rtol=1e-6, atol=1e-6)

    def test_transformer_block_fused_residual_on_off(self, monkeypatch):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers.transformer import (
            TransformerEncoderBlock)
        blk = TransformerEncoderBlock(n_in=16, n_heads=2, use_flash=False)
        blk.set_n_in(InputType.recurrent(16))
        params = blk.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 16))
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "0")
        off, _ = blk.forward(params, {}, x)
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "1")
        on, _ = blk.forward(params, {}, x)
        np.testing.assert_allclose(np.asarray(on), np.asarray(off),
                                   rtol=1e-5, atol=1e-5)


class TestFusedAdamKernel:
    """One-kernel packed-run Adam (kernels/fused_adam.py) vs the
    per-leaf jnp path — BIT-comparable inside jit (both sides compile;
    the containers always run the updater inside the jitted step)."""

    def _run(self, seed=3, gdtype=jnp.float32):
        rng = np.random.default_rng(seed)
        params = {"W": jnp.asarray(rng.standard_normal((4, 16, 16)),
                                   jnp.float32),
                  "b": jnp.asarray(rng.standard_normal((4, 16)),
                                   jnp.float32)}
        grads = {k: jnp.asarray(rng.standard_normal(v.shape), gdtype)
                 for k, v in params.items()}
        state = {k: {"m": jnp.asarray(rng.standard_normal(v.shape),
                                      jnp.float32) * 0.1,
                     "v": jnp.abs(jnp.asarray(
                         rng.standard_normal(v.shape), jnp.float32))
                     * 0.01}
                 for k, v in params.items()}
        return params, grads, state

    @pytest.mark.parametrize("gdtype", [jnp.float32, jnp.bfloat16])
    def test_bit_parity_vs_jnp_path(self, gdtype):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.kernels.fused_adam import (
            adam_update_packed)
        upd = Adam(0.01)
        params, grads, state = self._run(gdtype=gdtype)

        @jax.jit
        def kern(p, g, s):
            return adam_update_packed(upd, p, g, s, 7, interpret=True)

        @jax.jit
        def ref(p, g, s):
            out_p, out_s = {}, {}
            for pk, gg in g.items():
                gg = gg.astype(p[pk].dtype)
                delta, s2 = upd.apply(gg, s[pk], 7)
                out_p[pk] = p[pk] - delta.astype(p[pk].dtype)
                out_s[pk] = s2
            return out_p, out_s

        kp, ks = kern(params, grads, state)
        rp, rs = ref(params, grads, state)
        for pk in params:
            assert np.array_equal(np.asarray(kp[pk]), np.asarray(rp[pk]))
            assert np.array_equal(np.asarray(ks[pk]["m"]),
                                  np.asarray(rs[pk]["m"]))
            assert np.array_equal(np.asarray(ks[pk]["v"]),
                                  np.asarray(rs[pk]["v"]))
            assert kp[pk].dtype == jnp.float32    # fp32 master

    def test_schedule_lr(self):
        from deeplearning4j_tpu.common.schedules import ExponentialSchedule
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.kernels.fused_adam import (
            adam_update_packed)
        upd = Adam(ExponentialSchedule(0.01, 0.9))
        params, grads, state = self._run()
        kp, _ = jax.jit(lambda p, g, s: adam_update_packed(
            upd, p, g, s, 5, interpret=True))(params, grads, state)
        rp = {}
        for pk, gg in grads.items():
            delta, _ = upd.apply(gg, state[pk], 5)
            rp[pk] = params[pk] - delta
        for pk in params:
            np.testing.assert_allclose(np.asarray(kp[pk]),
                                       np.asarray(rp[pk]),
                                       rtol=1e-6, atol=1e-7)

    def test_eligibility(self, monkeypatch):
        from deeplearning4j_tpu.common.updaters import Adam, Nadam, Sgd
        from deeplearning4j_tpu.kernels.fused_adam import (
            fused_adam_eligible)
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "1")
        assert fused_adam_eligible(Adam(0.01))
        assert not fused_adam_eligible(Nadam(0.01))   # different math
        assert not fused_adam_eligible(Sgd(0.01))
        monkeypatch.setenv("DL4J_PALLAS_KERNELS", "0")
        assert not fused_adam_eligible(Adam(0.01))

    def test_flat_state_round_trip(self):
        # pre-flattened m/v ([rows, 128] lane-aligned, kept between
        # steps) must be an EXACT relayout of the per-leaf dicts
        from deeplearning4j_tpu.kernels.fused_adam import (
            FLAT_KEY,
            flatten_opt_state,
            is_flat_state,
            unflatten_opt_state,
        )
        params, _, state = self._run()
        flat = flatten_opt_state(params, state)
        assert is_flat_state(flat) and not is_flat_state(state)
        assert flat[FLAT_KEY]["m"].shape[1] == 128
        # idempotent both ways
        assert flatten_opt_state(params, flat) is flat
        assert unflatten_opt_state(params, state) is state
        back = unflatten_opt_state(params, flat)
        for pk in state:
            for s in ("m", "v"):
                assert np.array_equal(np.asarray(back[pk][s]),
                                      np.asarray(state[pk][s]))

    def test_flat_state_multi_step_bit_parity(self):
        # three consecutive updates carrying the FLAT form (what rides
        # a fused program's scan carry) vs three per-leaf-state updates
        # — params and (unflattened) m/v bit-identical, and the flat
        # path's output stays flat (no per-step relayout)
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.kernels.fused_adam import (
            adam_update_packed,
            flatten_opt_state,
            is_flat_state,
            unflatten_opt_state,
        )
        upd = Adam(0.01)
        params, grads, state = self._run(seed=11)

        @jax.jit
        def steps(p, s):
            for t in range(3):
                p, s = adam_update_packed(upd, p, grads, s, t,
                                          interpret=True)
            return p, s

        fp, fs = steps(params, flatten_opt_state(params, state))
        rp, rs = steps(params, state)
        assert is_flat_state(fs) and not is_flat_state(rs)
        fs = unflatten_opt_state(fp, fs)
        for pk in params:
            assert np.array_equal(np.asarray(fp[pk]), np.asarray(rp[pk]))
            for s in ("m", "v"):
                assert np.array_equal(np.asarray(fs[pk][s]),
                                      np.asarray(rs[pk][s]))

    def test_container_on_off_bit_identical(self, monkeypatch):
        # whole train loop: fused-Adam kernel vs jnp path over a packed
        # deep-MLP run — params AND updater state bit-identical
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        def run(env):
            monkeypatch.setenv("DL4J_PALLAS_KERNELS", env)
            b = (NeuralNetConfiguration.builder().seed(7)
                 .updater(Adam(0.01)).list())
            for _ in range(4):
                b = b.layer(DenseLayer(n_in=16, n_out=16,
                                       activation="tanh"))
            conf = (b.layer(OutputLayer(n_in=16, n_out=4,
                                        activation="softmax",
                                        loss="mcxent"))
                    .set_input_type(InputType.feed_forward(16)).build())
            net = MultiLayerNetwork(conf).init()
            rng = np.random.default_rng(0)
            x = rng.standard_normal((32, 16)).astype(np.float32)
            y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
            net.fit(x, y, epochs=2, batch_size=16, shuffle=False)
            return net

        on, off = run("1"), run("0")
        for a, b in zip(jax.tree_util.tree_leaves(on.params),
                        jax.tree_util.tree_leaves(off.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(on.updater_state),
                        jax.tree_util.tree_leaves(off.updater_state)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestFlashBf16:
    def test_flash_attention_bf16_inputs(self):
        # mixed_bf16 policy feeds the attention kernel bf16 q/k/v —
        # fp32 accumulation inside, parity vs the XLA path in bf16 band
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (2, 64, 2, 16), jnp.bfloat16)
                   for kk in ks)
        got = flash_attention(q, k, v, True, 32, 32, True)
        assert got.dtype == jnp.bfloat16
        want = _xla_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-2, atol=2e-2)
