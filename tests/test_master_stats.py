"""TrainingMaster round stats + timeline export, and the ProfilerListener
trace hook (reference: `ParameterAveragingTrainingMasterStats.java`,
`spark/stats/StatsUtils.java`; SURVEY §5 tracing row)."""

import os
import tempfile

import numpy as np
import jax
from jax.sharding import Mesh

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import ProfilerListener
from deeplearning4j_tpu.parallel import (
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    TrainingMasterStats,
)


def _model():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=5, n_out=12, activation="relu"))
            .layer(OutputLayer(n_in=12, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


class TestTrainingMasterStats:
    def test_param_averaging_collects_round_timeline(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, averaging_frequency=2, mesh=mesh,
            collect_training_stats=True)
        master.execute_training(_model(), _data(), epochs=2)
        stats = master.get_training_stats()
        assert stats is not None
        counts = stats.phase_counts()
        # no fault tolerance configured -> single fit() for all epochs,
        # so params broadcast exactly once
        assert counts.get("broadcast") == 1
        assert counts.get("local_fit", 0) >= 2
        assert counts.get("average", 0) >= 1
        assert stats.round_count >= 1
        totals = stats.phase_totals_ms()
        assert all(v >= 0 for v in totals.values())

    def test_shared_master_sync_steps_recorded(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                      collect_training_stats=True)
        master.execute_training(_model(), _data(), epochs=1)
        stats = master.get_training_stats()
        assert stats.phase_counts().get("sync_step", 0) >= 1

    def test_exports_and_listener_hook(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, averaging_frequency=1, mesh=mesh,
            collect_training_stats=True)
        events = []
        master.stats = None
        master.execute_training(_model(), _data(32), epochs=1)
        stats = master.get_training_stats()
        stats.add_listener(events.append)
        stats.record("average", 0.001, round=99)
        assert events and events[0]["phase"] == "average"
        with tempfile.TemporaryDirectory() as d:
            hp = stats.export_html(os.path.join(d, "timeline.html"))
            jp = stats.export_json(os.path.join(d, "timeline.json"))
            html = open(hp).read()
            assert "TrainingMaster timeline" in html and "local_fit" in html
            import json
            data = json.loads(open(jp).read())
            assert data["summary"]["events"] == len(data["timeline"])

    def test_stats_off_by_default(self):
        # opt-in like the reference's setCollectTrainingStats
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh)
        master.execute_training(_model(), _data(32), epochs=1)
        assert master.get_training_stats() is None


class TestProfilerListener:
    def test_trace_files_written(self):
        net = _model()
        x, y = _data(48)
        with tempfile.TemporaryDirectory() as d:
            pl = ProfilerListener(d, start_iteration=2, num_iterations=2)
            net.set_listeners(pl)
            net.fit(x, y, epochs=2, batch_size=16)
            dirs = pl.trace_dirs()
            assert dirs, "no profiler trace output written"
            assert any("epoch0" in p for p in dirs)


class TestMasterFaultTolerance:
    """Checkpoint/resume + retry (the TPU-era fault story replacing
    Spark executor re-runs)."""

    def test_checkpoints_written_and_resume(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        d = str(tmp_path / "ckpt")
        m1 = _model()
        master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                      checkpoint_dir=d, checkpoint_every=1)
        master.execute_training(m1, _data(), epochs=3)
        import glob
        ckpts = sorted(glob.glob(d + "/epoch*.zip"))
        assert len(ckpts) == 3
        # resume: a fresh master + model restores the latest epoch and
        # only runs the remaining ones
        m2 = _model()
        master2 = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                       checkpoint_dir=d, checkpoint_every=1)
        master2.execute_training(m2, _data(), epochs=4)
        assert len(sorted(glob.glob(d + "/epoch*.zip"))) == 4
        # restored params actually came from the checkpoint lineage: one
        # extra epoch of training from epoch2's params
        assert m2._initialized

    def test_retry_restores_after_failure(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        d = str(tmp_path / "ckpt")
        model = _model()
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, averaging_frequency=1, mesh=mesh,
            checkpoint_dir=d, checkpoint_every=1, max_retries=2)
        x, y = _data()
        calls = {"n": 0}
        # inject one transient failure into the trainer's epoch fit
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        orig_fit = ParallelTrainer.fit

        def flaky_fit(self, *a, **k):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("simulated preemption")
            return orig_fit(self, *a, **k)

        ParallelTrainer.fit = flaky_fit
        try:
            master.execute_training(model, (x, y), epochs=3)
        finally:
            ParallelTrainer.fit = orig_fit
        import glob
        assert len(sorted(glob.glob(d + "/epoch*.zip"))) == 3
        assert calls["n"] == 4  # 3 successes + 1 injected failure

    def test_retry_budget_exhausted_raises(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        model = _model()
        master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh)
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        orig_fit = ParallelTrainer.fit
        ParallelTrainer.fit = lambda self, *a, **k: (_ for _ in ()).throw(
            RuntimeError("down"))
        try:
            import pytest
            with pytest.raises(RuntimeError):
                master.execute_training(model, _data(), epochs=2)
        finally:
            ParallelTrainer.fit = orig_fit


def test_retry_before_first_checkpoint_restores_initial_state(tmp_path):
    # failure before any checkpoint: restore the INITIAL params and
    # iteration counter, not the partially-trained state
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    model = _model()
    init_w = np.asarray(model.params["0"]["W"]).copy()
    master = SharedTrainingMaster(
        batch_size_per_worker=16, mesh=mesh,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=10,
        max_retries=1)
    x, y = _data()
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    orig_fit = ParallelTrainer.fit
    calls = {"n": 0}

    def flaky(self, *a, **k):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom")
        return orig_fit(self, *a, **k)

    ParallelTrainer.fit = flaky
    try:
        master.execute_training(model, (x, y), epochs=2)
    finally:
        ParallelTrainer.fit = orig_fit
    # epoch0 trained, epoch1 failed -> full restart -> 2 more epochs
    assert calls["n"] == 4
    assert model.iteration_count > 0
    assert not np.allclose(np.asarray(model.params["0"]["W"]), init_w)


def test_retry_without_checkpoint_dir_uses_snapshot():
    # max_retries with NO checkpoint_dir must still retry from the
    # initial in-memory snapshot (regression)
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    model = _model()
    master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                  max_retries=1)
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    orig_fit = ParallelTrainer.fit
    calls = {"n": 0}

    def flaky(self, *a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient")
        return orig_fit(self, *a, **k)

    ParallelTrainer.fit = flaky
    try:
        master.execute_training(model, _data(), epochs=2)
    finally:
        ParallelTrainer.fit = orig_fit
    assert calls["n"] == 3   # 1 failure + 2 successful epochs


def test_master_falls_back_to_sharded_checkpoint(tmp_path):
    # when the zip gather is impossible, the master saves the Orbax
    # sharded format and resume still works
    import glob
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    d = str(tmp_path / "ck")
    model = _model()
    master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                  checkpoint_dir=d, checkpoint_every=1)
    from deeplearning4j_tpu.util.serializer import ModelSerializer
    orig_write = ModelSerializer.write_model
    ModelSerializer.write_model = staticmethod(
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("non-addressable shards")))
    try:
        master.execute_training(model, _data(), epochs=2)
    finally:
        ModelSerializer.write_model = orig_write
    ckpts = sorted(glob.glob(d + "/epoch*.ckpt"))
    assert len(ckpts) == 2 and not glob.glob(d + "/epoch*.zip")

    # resume from the sharded checkpoint lineage
    m2 = _model()
    master2 = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                   checkpoint_dir=d, checkpoint_every=1)
    master2.execute_training(m2, _data(), epochs=3)
    assert m2.epoch_count >= 1
    all_ckpts = (glob.glob(d + "/epoch*.ckpt") + glob.glob(d + "/epoch*.zip"))
    assert len(all_ckpts) == 3


def test_torn_zip_checkpoint_not_left_behind(tmp_path):
    # a gather failure midway through the zip write must not leave a
    # structurally valid epoch*.zip (it would restore as fresh weights)
    import glob
    import zipfile
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    d = str(tmp_path / "ck")
    model = _model()
    master = SharedTrainingMaster(batch_size_per_worker=16, mesh=mesh,
                                  checkpoint_dir=d, checkpoint_every=1)
    from deeplearning4j_tpu.util.serializer import ModelSerializer
    orig_write = ModelSerializer.write_model

    def torn_write(m, path, **kw):
        # simulate: zip created, then the param gather explodes
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("configuration.json", m.conf.to_json())
        raise RuntimeError("gather failed mid-write")

    ModelSerializer.write_model = staticmethod(torn_write)
    try:
        master.execute_training(model, _data(), epochs=1)
    finally:
        ModelSerializer.write_model = orig_write
    assert not glob.glob(d + "/epoch*.zip")
    assert not glob.glob(d + "/*.tmp")
    assert len(glob.glob(d + "/epoch*.ckpt")) == 1


class TestStatsRegistrySink:
    """Phase-event fan-out onto the unified telemetry core: every
    TrainingMasterStats event must land in the metrics registry (labeled
    counters + timers) and on the tracer's Perfetto timeline."""

    def _monitored(self):
        from deeplearning4j_tpu import monitor
        reg = monitor.MetricsRegistry()
        tr = monitor.Tracer()
        monitor.enable(registry=reg, tracer=tr)
        return monitor, reg, tr

    def _restore(self, monitor):
        monitor.disable()
        monitor._STATE.registry = monitor.GLOBAL_REGISTRY
        monitor._STATE.tracer = monitor.GLOBAL_TRACER

    def test_listener_fanout_order_and_payload(self):
        stats = TrainingMasterStats()
        seen_a, seen_b = [], []
        stats.add_listener(seen_a.append)
        stats.add_listener(seen_b.append)
        with stats.time_phase("local_fit", round=0):
            pass
        stats.record("average", 0.002, round=0)
        assert [e["phase"] for e in seen_a] == ["local_fit", "average"]
        assert seen_a == seen_b == stats.events
        for ev in seen_a:
            assert ev["duration_ms"] >= 0 and "start_ms" in ev

    def test_parallel_trainer_routes_to_registry(self):
        from deeplearning4j_tpu.parallel import ParallelTrainer
        monitor, reg, tr = self._monitored()
        try:
            mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
            trainer = ParallelTrainer(_model(), mesh, mode="sync",
                                      stats=TrainingMasterStats())
            x, y = _data(32)
            trainer.fit(x, y, epochs=1, batch_size=16)
            expo = reg.exposition()
            assert "parallel_phase_total" in expo
            assert 'phase="sync_step"' in expo
            assert reg.counter("parallel_phase_total",
                               phase="sync_step").value >= 1
            # distributed phases share the fit timeline (Perfetto export)
            names = tr.span_names()
            assert any(n.startswith("master/") for n in names)
            # the MonitorListener also rode the trainer's listener bus
            assert reg.counter("training_iterations_total",
                               model="default").value >= 2
        finally:
            self._restore(monitor)

    def test_sharded_trainer_stats_seam(self):
        from deeplearning4j_tpu.parallel import (MeshSpec,
                                                 ShardedParallelTrainer,
                                                 make_mesh)
        monitor, reg, _ = self._monitored()
        try:
            mesh = make_mesh(MeshSpec.of(data=2, model=2))
            stats = TrainingMasterStats()
            trainer = ShardedParallelTrainer(_model(), mesh, stats=stats)
            x, y = _data(32)
            trainer.fit(x, y, epochs=1, batch_size=16)
            counts = stats.phase_counts()
            assert counts.get("broadcast") == 1
            assert counts.get("sync_step", 0) >= 2
            assert reg.timer("parallel_phase_seconds",
                             phase="sync_step").count >= 2
        finally:
            self._restore(monitor)

    def test_rebind_is_idempotent_across_fits(self):
        from deeplearning4j_tpu import monitor as mon
        monitor, reg, _ = self._monitored()
        try:
            stats = TrainingMasterStats()
            mon.attach_master_stats(stats)
            mon.attach_master_stats(stats)  # trainers re-attach every fit
            stats.record("average", 0.001)
            assert reg.counter("parallel_phase_total",
                               phase="average").value == 1
        finally:
            self._restore(monitor)

    def test_timeline_export_roundtrip_with_sink(self, tmp_path):
        monitor, reg, tr = self._monitored()
        try:
            stats = TrainingMasterStats()
            monitor.attach_master_stats(stats)
            stats.record("broadcast", 0.001, round=0)
            stats.record("local_fit", 0.02, round=0)
            # the master's own JSON/HTML exports still round-trip
            import json
            data = json.loads(stats.to_json())
            assert data["summary"]["phase_counts"]["local_fit"] == 1
            hp = stats.export_html(str(tmp_path / "t.html"))
            assert "local_fit" in open(hp).read()
            # and the same events are on the Perfetto timeline
            doc = json.loads(tr.export_chrome_trace())
            assert {e["name"] for e in doc["traceEvents"]} == {
                "master/broadcast", "master/local_fit"}
        finally:
            self._restore(monitor)


def test_shared_master_fused_steps():
    """SharedTrainingMaster(steps_per_execution=k) drains k-step groups
    through one dispatch and still trains every batch."""
    net = _model()
    x, y = _data()
    master = SharedTrainingMaster(batch_size_per_worker=4,
                                  steps_per_execution=2)
    master.execute_training(net, (x, y), epochs=2)
    assert net.epoch_count == 2
    for v in net.param_table().values():
        assert np.all(np.isfinite(np.asarray(v)))


class TestMasterEvaluate:
    """Distributed evaluation through the masters (reference: Spark
    eval functions + treeAggregate): per-shard Evaluations combined
    with Evaluation.merge must equal a single-pass host evaluation."""

    def _net(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=5, n_out=12, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_master_evaluate_matches_host(self):
        import numpy as np
        from deeplearning4j_tpu.eval import Evaluation
        from deeplearning4j_tpu.parallel.master import (
            ParameterAveragingTrainingMaster, SharedTrainingMaster,
        )
        net = self._net()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((96, 5)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 96)]
        host = Evaluation()
        host.eval(y, np.asarray(net.output(x)))
        for master in (ParameterAveragingTrainingMaster(),
                       SharedTrainingMaster()):
            ev = master.execute_evaluation(net, (x, y), batch_size=16)
            assert ev.total == 96
            np.testing.assert_array_equal(ev.confusion.matrix,
                                          host.confusion.matrix)

    def test_master_evaluate_preserves_evaluation_config(self):
        """Caller-supplied evaluation settings (decision threshold) must
        apply on every shard, not just in the merged container."""
        import numpy as np
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.eval import Evaluation
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.master import SharedTrainingMaster
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=5, n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
        thr = 0.9
        host = Evaluation(binary_decision_threshold=thr)
        host.eval(y, np.asarray(net.output(x)))
        ev = SharedTrainingMaster().execute_evaluation(
            net, (x, y), batch_size=16,
            evaluation=Evaluation(binary_decision_threshold=thr))
        np.testing.assert_array_equal(ev.confusion.matrix,
                                      host.confusion.matrix)
