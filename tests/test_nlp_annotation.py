"""UIMA-style annotation pipeline (reference role:
deeplearning4j-nlp-uima — AnalysisEngine aggregates feeding
UimaSentenceIterator / UimaTokenizerFactory)."""

import pytest

from deeplearning4j_tpu.nlp.annotation import (
    AnnotationPipeline,
    AnnotationSentenceIterator,
    AnnotationTokenizerFactory,
    POSAnnotator,
    SentenceAnnotator,
    StemAnnotator,
    TokenAnnotator,
    default_pipeline,
)


class TestSentenceAnnotator:
    def _sentences(self, text):
        doc = AnnotationPipeline([SentenceAnnotator()]).annotate(text)
        return [doc.covered_text(s) for s in doc.select("sentence")]

    def test_splits_on_terminators(self):
        s = self._sentences("The cat sat. The dog ran! Did it rain? Yes.")
        assert s == ["The cat sat.", "The dog ran!", "Did it rain?",
                     "Yes."]

    def test_abbreviation_guard(self):
        s = self._sentences("Dr. Smith arrived. He sat down.")
        assert s == ["Dr. Smith arrived.", "He sat down."]

    def test_offsets_cover_original_text(self):
        text = "One sentence here. And two."
        doc = AnnotationPipeline([SentenceAnnotator()]).annotate(text)
        for a in doc.select("sentence"):
            assert text[a.begin:a.end] == doc.covered_text(a)


class TestTokenAndPOS:
    def test_tokens_within_sentences(self):
        doc = default_pipeline().annotate("The cat sat. Dogs run quickly.")
        sents = doc.select("sentence")
        toks0 = doc.covered("token", sents[0])
        assert [doc.covered_text(t) for t in toks0] == ["The", "cat",
                                                        "sat"]

    def test_pos_features(self):
        doc = default_pipeline().annotate("The cat is running quickly.")
        tags = {doc.covered_text(t): t.features["pos"]
                for t in doc.select("token")}
        assert tags["The"] == "DT"
        assert tags["is"] == "VB"
        assert tags["running"] == "VBG"
        assert tags["quickly"] == "RB"
        assert tags["cat"] == "NN"

    def test_stemmer(self):
        doc = default_pipeline(stem=True).annotate("cats running played")
        stems = [t.features["stem"] for t in doc.select("token")]
        assert stems == ["cat", "runn", "play"]

    def test_pluggable_tokenizer_factory(self):
        # the CJK segmenter drives the token annotator unchanged
        from deeplearning4j_tpu.nlp.cjk import (
            CJKTokenizerFactory, DictionarySegmenter)
        tf = CJKTokenizerFactory({"深度": 1.0, "学习": 1.0})
        doc = AnnotationPipeline(
            [SentenceAnnotator(), TokenAnnotator(tf)]).annotate("深度学习")
        toks = [t.features["surface"] for t in doc.select("token")]
        assert toks == ["深度", "学习"]


class TestPipelineSeams:
    DOCS = ["The cat sat on the mat. The dog barked.",
            "Markets rose today. Banks invested heavily."]

    def test_sentence_iterator(self):
        it = AnnotationSentenceIterator(self.DOCS)
        out = []
        while it.has_next():
            out.append(it.next_sentence())
        assert len(out) == 4
        assert out[0] == "The cat sat on the mat."
        it.reset()
        assert it.has_next()

    def test_tokenizer_factory_pos_filter(self):
        tf = AnnotationTokenizerFactory(
            pos_keep=frozenset({"NN", "NNS", "NNP", "VBD"}))
        toks = tf.create("The cat sat on the big mat").get_tokens()
        assert "The" not in toks and "on" not in toks
        assert "cat" in toks and "mat" in toks

    def test_word2vec_through_annotation_factory(self):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        corpus = ["the cat chased the mouse", "the dog chased the cat",
                  "banks move markets", "markets follow banks"] * 6
        w2v = Word2Vec(sentence_iterator=corpus,
                       tokenizer_factory=AnnotationTokenizerFactory(),
                       layer_size=8, window_size=2, min_word_frequency=2,
                       epochs=1, batch_size=64, seed=0)
        w2v.fit()
        assert w2v.has_word("cat") and w2v.has_word("markets")
