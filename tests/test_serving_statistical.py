"""Distributional contract of sampled speculation (`-m statistical`).

Rejection sampling over delta drafts promises each emitted token is
MARGINALLY a vanilla sample from the target's filtered/tempered
distribution `q_t` given its prefix — a distributional contract, not a
bit one (docs/SERVING.md acceptance-oracle table; the greedy contract
stays bit-exact and is enforced in test_serving_spec.py /
test_serving_rs.py).  These tests hold that contract with chi-square
goodness-of-fit over large-sample token marginals.

DETERMINISM + FALSE-POSITIVE BUDGET: every test pins its seeds, so
tier-1 runs are bit-reproducible; the chi-square thresholds are the
q = 1 - 1e-4 quantiles, so even under seed churn a correct
implementation fails any single test with probability < 1e-4.  Sample
sizes (documented per test) are chosen so the tests also have power:
at n = 20000 a total-variation defect of ~2% in a 6-atom marginal
drives the statistic past the threshold with near-certainty.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.zoo.transformer import (
    filter_logits,
    rejection_sample_drafts,
)

V = 23

pytestmark = pytest.mark.statistical


def chi2_crit(df: int, q: float = 0.9999) -> float:
    """Upper chi-square quantile: scipy when present, Wilson-Hilferty
    otherwise (accurate to ~1% at these df — the +5% safety margin in
    the callers swamps it)."""
    try:
        from scipy.stats import chi2
        return float(chi2.ppf(q, df))
    except Exception:  # noqa: BLE001 — scipy is optional
        z = 3.719      # standard normal quantile at 1 - 1e-4
        a = 2.0 / (9.0 * df)
        return df * (1.0 - a + z * np.sqrt(a)) ** 3


def chi2_stat(counts: np.ndarray, expected: np.ndarray) -> float:
    keep = expected > 0
    return float(((counts[keep] - expected[keep]) ** 2
                  / expected[keep]).sum())


def target_dist(probs_row: np.ndarray, temp: float, top_k, top_p):
    """The exact q_t the engine samples from: the
    `filter_logits(log(clip(p, 1e-9)) / T, top_k, top_p)` chain
    `_sample_ids` and `rejection_sample_drafts` share — replayed once
    here to get analytic expected counts."""
    logits = jnp.log(jnp.clip(jnp.asarray(probs_row, jnp.float32),
                              1e-9)) / temp
    logits = filter_logits(logits[None, :], top_k,
                           None if top_p is None else
                           jnp.full((1, 1), top_p, jnp.float32))
    return np.asarray(jax.nn.softmax(logits, axis=-1))[0]


def run_rs(probs, token_mat, n_valid, keys, temp, top_p=None, top_k=None):
    S = probs.shape[0]
    out = rejection_sample_drafts(
        jnp.asarray(probs, jnp.float32),
        jnp.asarray(token_mat, jnp.int32),
        jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(keys, jnp.uint32),
        jnp.zeros(S, jnp.int32),
        jnp.full(S, temp, jnp.float32),
        None if top_p is None else jnp.full(S, top_p, jnp.float32),
        top_k)
    return np.asarray(out[0]), np.asarray(out[1])


def emitted_first_token(n_acc, final, token_mat):
    """The token a slot emits at the FIRST speculative position: the
    draft when lane 1 was accepted, the residual/bonus sample when
    not."""
    return np.where(n_acc >= 1, token_mat[:, 1], final)


class TestMarginalChiSquare:
    """n = 20000 rows per case; every row is an independent
    (key, accept-test, resample) chain with the SAME target
    distribution, so token counts are multinomial(n, q_t)."""

    N = 20000

    def _case(self, seed, temp, top_k, top_p, peak):
        rng = np.random.default_rng(seed)
        base = rng.dirichlet(np.ones(V) * 0.4)
        base = 0.5 * base + 0.5 * peak
        probs = np.broadcast_to(base, (self.N, 2, V)).astype(np.float32)
        qt = target_dist(base, temp, top_k, top_p)
        draft = int(np.argsort(qt)[-2])       # a plausible-but-not-top
        token_mat = np.zeros((self.N, 2), np.int32)
        token_mat[:, 1] = draft
        keys = np.asarray(rng.integers(0, 2**32, (self.N, 2)), np.uint32)
        n_acc, final = run_rs(probs, token_mat,
                              np.full(self.N, 2, np.int32), keys,
                              temp, top_p, top_k)
        emitted = emitted_first_token(n_acc, final, token_mat)
        return qt, emitted

    def _assert_fits(self, qt, emitted):
        expected = qt * len(emitted)
        # chi-square needs expected counts >= ~5: lump the tail mass
        big = expected >= 5.0
        counts = np.bincount(emitted, minlength=V).astype(float)
        obs = np.append(counts[big], counts[~big].sum())
        exp = np.append(expected[big], expected[~big].sum())
        df = len(obs) - 1
        stat = chi2_stat(obs, exp)
        assert stat < 1.05 * chi2_crit(df), (
            f"chi2={stat:.1f} over df={df} exceeds the 1e-4 critical "
            f"value {chi2_crit(df):.1f} — the emitted marginal has "
            f"drifted from the target distribution")

    def test_marginal_matches_target_plain(self):
        peak = np.zeros(V)
        peak[[2, 5, 9]] = [0.5, 0.3, 0.2]
        qt, emitted = self._case(seed=101, temp=1.0, top_k=None,
                                 top_p=None, peak=peak)
        self._assert_fits(qt, emitted)

    def test_marginal_matches_target_tempered_topk(self):
        peak = np.zeros(V)
        peak[[1, 3, 4, 8]] = [0.4, 0.3, 0.2, 0.1]
        qt, emitted = self._case(seed=102, temp=0.7, top_k=6,
                                 top_p=None, peak=peak)
        self._assert_fits(qt, emitted)

    def test_marginal_matches_target_nucleus(self):
        peak = np.zeros(V)
        peak[[0, 7, 11, 19]] = [0.35, 0.3, 0.2, 0.15]
        qt, emitted = self._case(seed=103, temp=1.2, top_k=None,
                                 top_p=0.9, peak=peak)
        self._assert_fits(qt, emitted)

    def test_matches_vanilla_sampler_two_sample(self):
        """Rejection-path emissions vs `jax.random.categorical` draws
        from the SAME filtered logits (matched temperature/top-k/top-p
        — the vanilla `_sample_ids` tail): two-sample chi-square
        homogeneity at n = 20000 per arm."""
        rng = np.random.default_rng(104)
        peak = np.zeros(V)
        peak[[2, 6, 13]] = [0.45, 0.35, 0.2]
        qt, emitted = self._case(seed=104, temp=0.8, top_k=8,
                                 top_p=None, peak=peak)
        n = len(emitted)
        vkeys = jax.vmap(jax.random.PRNGKey)(
            jnp.asarray(rng.integers(0, 2**31, n)))
        logits = jnp.log(jnp.clip(jnp.asarray(qt, jnp.float32), 1e-12))
        vanilla = np.asarray(jax.vmap(
            lambda k: jax.random.categorical(k, logits))(vkeys))
        c1 = np.bincount(emitted, minlength=V).astype(float)
        c2 = np.bincount(vanilla, minlength=V).astype(float)
        tot = c1 + c2
        big = tot >= 10.0
        c1 = np.append(c1[big], c1[~big].sum())
        c2 = np.append(c2[big], c2[~big].sum())
        tot = c1 + c2
        keep = tot > 0
        # standard 2xk homogeneity statistic, df = k-1 (equal arms)
        exp1, exp2 = tot[keep] / 2.0, tot[keep] / 2.0
        stat = chi2_stat(c1[keep], exp1) + chi2_stat(c2[keep], exp2)
        df = int(keep.sum()) - 1
        assert stat < 1.05 * chi2_crit(df), (
            f"chi2={stat:.1f} over df={df}: rejection-sampling "
            f"emissions are distinguishable from vanilla sampling")

    def test_acceptance_rate_tracks_draft_mass(self):
        """E[n_acc at lane 1] = q_t(d): binomial check at n = 20000
        (sigma ~= 0.0035) — 5-sigma tolerance."""
        peak = np.zeros(V)
        peak[[4, 10]] = [0.6, 0.4]
        qt_emitted = self._case(seed=105, temp=1.0, top_k=None,
                                top_p=None, peak=peak)
        qt, _ = qt_emitted
        rng = np.random.default_rng(105)
        base = rng.dirichlet(np.ones(V) * 0.4)
        base = 0.5 * base + 0.5 * peak
        probs = np.broadcast_to(base, (self.N, 2, V)).astype(np.float32)
        draft = int(np.argsort(qt)[-2])
        token_mat = np.zeros((self.N, 2), np.int32)
        token_mat[:, 1] = draft
        keys = np.asarray(rng.integers(0, 2**32, (self.N, 2)), np.uint32)
        n_acc, _ = run_rs(probs, token_mat,
                          np.full(self.N, 2, np.int32), keys, 1.0)
        assert abs(n_acc.mean() - qt[draft]) < 5 * np.sqrt(
            qt[draft] * (1 - qt[draft]) / self.N)
