"""Produce the packaged LeNet pretrained checkpoint.

Trains the zoo LeNet on the real sklearn handwritten-digits corpus
(1797 8x8 grayscale digits, bilinearly upscaled to LeNet's 28x28 input)
and writes a ModelSerializer zip into the package at
`deeplearning4j_tpu/zoo/weights/` — the artifact `LeNet.pretrained_url`
points at, so `init_pretrained()` executes its full download → checksum
→ restore path end-to-end (reference `ZooModel.initPretrained:52-81`).

    python tests/make_zoo_pretrained.py
"""

import hashlib
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parents[1]))

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

WEIGHTS_DIR = (Path(__file__).parents[1] / "deeplearning4j_tpu" / "zoo"
               / "weights")


def load_digits_28x28():
    from sklearn.datasets import load_digits
    d = load_digits()
    x = d.images.astype(np.float32) / 16.0          # [N, 8, 8] in [0,1]
    # bilinear 8x8 -> 28x28 via jax.image to avoid a scipy dependency
    import jax.image
    import jax.numpy as jnp
    x = np.asarray(jax.image.resize(jnp.asarray(x), (x.shape[0], 28, 28),
                                    "bilinear"))
    y = np.eye(10, dtype=np.float32)[d.target]
    return x[..., None], y


def main():
    from deeplearning4j_tpu.eval import Evaluation
    from deeplearning4j_tpu.util.serializer import ModelSerializer
    from deeplearning4j_tpu.zoo.lenet import LeNet

    x, y = load_digits_28x28()
    rng = np.random.default_rng(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_test = 297
    xtr, ytr, xte, yte = x[n_test:], y[n_test:], x[:n_test], y[:n_test]

    net = LeNet(num_classes=10).init()
    net.fit(xtr, ytr, epochs=8, batch_size=100)

    ev = Evaluation(10)
    ev.eval(yte, np.asarray(net.output(xte)))
    acc = ev.accuracy()
    print(f"held-out accuracy: {acc:.4f}")
    assert acc > 0.93, "pretrained artifact would be junk — not saving"

    WEIGHTS_DIR.mkdir(parents=True, exist_ok=True)
    dest = WEIGHTS_DIR / "lenet_mnist.zip"
    ModelSerializer.write_model(net, dest, save_updater=False)
    checksum = hashlib.sha256(dest.read_bytes()).hexdigest()
    # merge into the filename-keyed manifest — a wholesale overwrite
    # would clobber the other packaged artifacts' entries
    manifest_path = WEIGHTS_DIR / "MANIFEST.json"
    manifest = (json.loads(manifest_path.read_text())
                if manifest_path.exists() else {})
    if "file" in manifest:  # migrate the old single-entry layout
        manifest = {manifest["file"]: manifest}
    manifest["lenet_mnist.zip"] = {
        "sha256": checksum,
        "holdout_accuracy": round(float(acc), 4),
        "train_corpus": "sklearn load_digits (1797 real 8x8 digits) "
                        "upscaled bilinear to 28x28",
        "generator": "tests/make_zoo_pretrained.py",
    }
    manifest_path.write_text(json.dumps(manifest, indent=2))
    print(json.dumps(manifest["lenet_mnist.zip"], indent=2))


if __name__ == "__main__":
    main()
