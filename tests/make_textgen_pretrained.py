"""Produce the packaged TextGenerationLSTM pretrained checkpoint.

Trains the zoo char-RNN on this repository's own documentation (real
English prose, fully reproducible from the repo — no download), and
writes a ModelSerializer zip + charset manifest into
`deeplearning4j_tpu/zoo/weights/` for `TextGenerationLSTM.
init_pretrained(PretrainedType.TEXT)` (reference
`ZooModel.initPretrained` :52-81; the reference hosted its char-RNN
weights the same way).

    python tests/make_textgen_pretrained.py
"""

import hashlib
import json
import os
import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parents[1]))

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

REPO = Path(__file__).parents[1]
WEIGHTS_DIR = REPO / "deeplearning4j_tpu" / "zoo" / "weights"
VOCAB, T = 77, 100


def load_corpus():
    parts = []
    for p in [REPO / "README.md", *sorted((REPO / "docs").glob("*.md")),
              REPO / "SURVEY.md"]:
        parts.append(p.read_text(errors="ignore"))
    return "\n".join(parts)


def build_charset(text):
    # top VOCAB-1 characters by frequency; everything else maps to the
    # final "unknown" slot
    common = [c for c, _ in Counter(text).most_common(VOCAB - 1)]
    return "".join(sorted(common))


def encode(text, charset):
    idx = {c: i for i, c in enumerate(charset)}
    return np.array([idx.get(c, VOCAB - 1) for c in text], np.int32)


def windows(ids):
    n = (len(ids) - 1) // T
    x = ids[:n * T].reshape(n, T)
    y = ids[1:n * T + 1].reshape(n, T)
    eye = np.eye(VOCAB, dtype=np.float32)
    return eye[x], eye[y]


def main():
    from deeplearning4j_tpu.util.serializer import ModelSerializer
    from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM

    text = load_corpus()
    charset = build_charset(text)
    ids = encode(text, charset)
    x, y = windows(ids)
    rng = np.random.default_rng(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_test = max(len(x) // 10, 8)
    xtr, ytr, xte, yte = x[n_test:], y[n_test:], x[:n_test], y[:n_test]
    print(f"corpus {len(text)} chars → {len(x)} windows of {T}")

    model = TextGenerationLSTM(vocab_size=VOCAB, hidden=128, tbptt_length=T)
    net = model.init()
    for epoch in range(30):
        net.fit(xtr, ytr, epochs=1, batch_size=32, steps_per_execution=4)
        out = np.asarray(net.output(xte))
        acc = float(np.mean(out.argmax(-1) == yte.argmax(-1)))
        print(f"epoch {epoch}: held-out next-char accuracy {acc:.4f}")
        if acc > 0.45:
            break
    assert acc > 0.40, "char model too weak to ship"

    WEIGHTS_DIR.mkdir(parents=True, exist_ok=True)
    dest = WEIGHTS_DIR / "textgen_docs.zip"
    ModelSerializer.write_model(net, dest, save_updater=False)
    checksum = hashlib.sha256(dest.read_bytes()).hexdigest()
    manifest_path = WEIGHTS_DIR / "MANIFEST.json"
    manifest = json.loads(manifest_path.read_text()) \
        if manifest_path.exists() else {}
    if "file" in manifest:  # migrate the round-4 single-entry layout
        manifest = {"lenet_mnist.zip": manifest}
    manifest["textgen_docs.zip"] = {
        "sha256": checksum,
        "holdout_next_char_accuracy": round(acc, 4),
        "charset": charset,
        "train_corpus": "this repository's README/docs/SURVEY markdown "
                        f"({len(text)} chars)",
        "generator": "tests/make_textgen_pretrained.py",
    }
    manifest_path.write_text(json.dumps(manifest, indent=2))
    print(json.dumps({k: v for k, v in manifest["textgen_docs.zip"].items()
                      if k != "charset"}, indent=2))


if __name__ == "__main__":
    main()
