"""Token-goodput ledger + TTFT decomposition (ISSUE: observability).

Contracts:

- conservation BY CONSTRUCTION: ``sum(classes) == dispatched_total`` at
  every instant, across per-class accounting AND warmup/drain mode
  routing — test-enforced over real serving runs;
- a warmed server's run lands strictly inside (0, 1): warmup work is on
  the books (never in ``useful``), served tokens are, and the registry
  mirror carries the same totals without ever seeing a negative delta;
- goodput accounting adds ZERO device syncs (`block_until_ready` count
  identical monitored vs unmonitored — the request-tracing contract);
- `ttft_decomposition` splits TTFT into queue-wait / prefill /
  first-emit from host stamps alone; a shed request (no prefill phase)
  yields None.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import MetricsRegistry, Tracer
from deeplearning4j_tpu.monitor.goodput import (
    GOODPUT_CLASSES,
    GOODPUT_COUNTER_FAMILIES,
    GoodputLedger,
    ttft_decomposition,
)
from deeplearning4j_tpu.serving import GenerationServer
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 32
BL = 4


@pytest.fixture(scope="module")
def net():
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=3).init()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 3))


@pytest.fixture
def mon():
    reg, tr = MetricsRegistry(), Tracer()
    monitor.enable(registry=reg, tracer=tr)
    yield reg, tr
    monitor.disable()
    monitor._STATE.registry = monitor.GLOBAL_REGISTRY
    monitor._STATE.tracer = monitor.GLOBAL_TRACER


def _serve(srv, prompts, n=6, n_tokens=6):
    streams = [srv.generate_async(prompts[r % len(prompts)], n_tokens)
               for r in range(n)]
    toks = np.stack([s.result(timeout=300) for s in streams])
    return streams, toks


# ============================================================== ledger
class TestLedgerUnit:
    def test_conservation_by_construction(self):
        lg = GoodputLedger()
        lg.account(useful=5, pad_waste=3)
        lg.account(useful=2, spec_rejected=4, preempt_discard=1)
        assert lg.dispatched_total == 15
        assert sum(lg.classes.values()) == lg.dispatched_total
        assert lg.conserved()
        assert lg.classes["useful"] == 7
        assert lg.goodput_fraction() == pytest.approx(7 / 15)

    def test_mode_routes_everything(self):
        lg = GoodputLedger()
        lg.set_mode("warmup")
        lg.account(useful=8, pad_waste=2)
        assert lg.classes["warmup"] == 10 and lg.classes["useful"] == 0
        lg.set_mode(None)
        lg.account(useful=5)
        lg.set_mode("drain")
        lg.account(useful=3, pad_waste=1)
        assert lg.classes["drain"] == 4
        assert lg.conserved()
        # drain + warmup never count as useful
        assert lg.goodput_fraction() == pytest.approx(5 / 19)

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            GoodputLedger().set_mode("lunch")

    def test_negative_class_rejected(self):
        lg = GoodputLedger()
        with pytest.raises(ValueError, match="non-negative"):
            lg.account(useful=5, pad_waste=-1)

    def test_zero_total_is_noop_and_fraction_honest_zero(self):
        lg = GoodputLedger()
        lg.account()                 # nothing dispatched, nothing booked
        assert lg.dispatched_total == 0
        # honest zero, never a flattering 1.0
        assert lg.goodput_fraction() == 0.0

    def test_snapshot_carries_totals(self):
        lg = GoodputLedger()
        lg.account(useful=4, pad_waste=4)
        snap = lg.snapshot()
        assert snap["dispatched_total"] == 8
        assert snap["goodput_fraction"] == pytest.approx(0.5)
        for c in GOODPUT_CLASSES:
            assert c in snap


# ===================================================== serving runs
class TestServingConservation:
    def test_warmed_run_conserves_and_mirrors(self, mon, net, prompts):
        reg, _ = mon
        srv = GenerationServer(net, n_slots=2, n_blocks=16, block_len=BL)
        srv.warmup(3, 6).start()
        try:
            _serve(srv, prompts)
        finally:
            srv.stop()
        lg = srv.engine.goodput
        assert lg.conserved()
        assert lg.classes["warmup"] > 0          # the compile grid
        assert lg.classes["useful"] > 0          # the served tokens
        assert lg.mode is None                   # bracket restored
        assert 0.0 < lg.goodput_fraction() < 1.0
        # the registry mirror carries the same totals (delta-published,
        # monotone — no negative increments possible)
        snap = reg.snapshot()
        for cls, fam in GOODPUT_COUNTER_FAMILIES.items():
            vals = snap.get(fam, {"values": []})["values"]
            mirrored = sum(v["value"] for v in vals)
            assert mirrored == lg.classes[cls], (cls, mirrored)
        frac = snap["serving_goodput_fraction"]["values"][0]["value"]
        assert frac == pytest.approx(lg.goodput_fraction())

    def test_unmonitored_run_still_accounts(self, net, prompts):
        assert not monitor.is_enabled()
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            _serve(srv, prompts)
        finally:
            srv.stop()
        lg = srv.engine.goodput
        assert lg.conserved() and lg.dispatched_total > 0
        assert lg.classes["useful"] > 0

    def test_speculative_run_conserves(self, net):
        prompt = np.asarray([1, 2, 3, 1, 2, 3], np.int64)
        srv = GenerationServer(net, n_slots=1, n_blocks=16,
                               block_len=BL, speculative=4).start()
        try:
            srv.generate_async(prompt, 20).result(timeout=300)
            proposed = srv.engine.spec_proposed_total
            accepted = srv.engine.spec_accepted_total
        finally:
            srv.stop()
        lg = srv.engine.goodput
        assert lg.conserved()
        if proposed > accepted:      # any rejection must be on the books
            assert lg.classes["spec_rejected"] > 0

    def test_drain_flips_mode(self, net, prompts):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            _serve(srv, prompts, n=2)
            assert srv.drain(timeout=60)
            assert srv.engine.goodput.mode == "drain"
            assert srv.engine.goodput.conserved()
        finally:
            srv.stop()


# =============================================== zero-device-sync
class TestGoodputSyncContract:
    """The ledger is host ints fed from values the scheduler already
    materialized: the monitored run (ledger mirror + gauges live)
    performs exactly the device syncs the unmonitored run does."""

    @pytest.fixture
    def sync_counter(self, monkeypatch):
        calls = {"n": 0}
        real = jax.block_until_ready

        def counting(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(jax, "block_until_ready", counting)
        return calls

    def test_monitored_equals_unmonitored_syncs(self, sync_counter, net,
                                                prompts):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            _serve(srv, prompts)
        finally:
            srv.stop()
        off = sync_counter["n"]
        ledger_off = srv.engine.goodput
        monitor.enable(registry=MetricsRegistry(), tracer=Tracer())
        try:
            srv = GenerationServer(net, n_slots=2, n_blocks=16,
                                   block_len=BL).start()
            try:
                _serve(srv, prompts)
            finally:
                srv.stop()
        finally:
            monitor.disable()
            monitor._STATE.registry = monitor.GLOBAL_REGISTRY
            monitor._STATE.tracer = monitor.GLOBAL_TRACER
        assert sync_counter["n"] == 2 * off
        # pad_waste rides wave composition (thread-timing dependent),
        # but the USEFUL work — prompts prefilled + tokens kept — is
        # identical, and both runs conserve
        assert srv.engine.goodput.classes["useful"] \
            == ledger_off.classes["useful"]
        assert srv.engine.goodput.conserved() and ledger_off.conserved()


# ========================================== TTFT decomposition
class TestTTFTDecomposition:
    def test_splits_from_host_stamps(self):
        tr = {"phases": [
                  {"name": "queued", "t0": 1.0, "t1": 1.5, "args": {}},
                  {"name": "prefill", "t0": 1.5, "t1": 1.8, "args": {}},
                  {"name": "decode", "t0": 1.8, "t1": 2.0, "args": {}}],
              "meta": {"ttft_s": 1.0}}
        dec = ttft_decomposition(tr)
        assert dec["queue_wait_s"] == pytest.approx(0.5)
        assert dec["prefill_s"] == pytest.approx(0.3)
        assert dec["first_emit_s"] == pytest.approx(0.2)
        assert dec["ttft_s"] == pytest.approx(1.0)

    def test_shed_trace_yields_none(self):
        tr = {"phases": [{"name": "queued", "t0": 0.0, "t1": 0.2,
                          "args": {}}],
              "meta": {}}
        assert ttft_decomposition(tr) is None

    def test_missing_ttft_annotation_degrades(self):
        tr = {"phases": [
                  {"name": "queued", "t0": 0.0, "t1": 0.4, "args": {}},
                  {"name": "prefill", "t0": 0.4, "t1": 0.6, "args": {}}],
              "meta": {}}
        dec = ttft_decomposition(tr)
        assert dec["first_emit_s"] == 0.0
        assert dec["ttft_s"] == pytest.approx(0.6)

    def test_real_traces_decompose_and_sum(self, mon, net, prompts):
        srv = GenerationServer(net, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams, _ = _serve(srv, prompts)
        finally:
            srv.stop()
        for s in streams:
            dec = ttft_decomposition(s.trace)
            assert dec is not None
            assert min(dec.values()) >= 0.0
            assert (dec["queue_wait_s"] + dec["prefill_s"]
                    + dec["first_emit_s"]) == pytest.approx(
                        dec["ttft_s"], abs=1e-9)
            assert dec["ttft_s"] == pytest.approx(
                s.trace.meta["ttft_s"])
