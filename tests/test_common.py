"""Tests for activations, losses, updaters, schedules, weight init,
distributions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.activations import ACTIVATIONS, get_activation
from deeplearning4j_tpu.common.losses import (
    LossBinaryXENT,
    LossMCXENT,
    LossMSE,
    get_loss,
    loss_from_dict,
)
from deeplearning4j_tpu.common.schedules import (
    ExponentialSchedule,
    FixedSchedule,
    MapSchedule,
    StepSchedule,
    WarmupCosineSchedule,
    schedule_from_dict,
)
from deeplearning4j_tpu.common.updaters import (
    Adam,
    AdaDelta,
    AdaGrad,
    AdaMax,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
    updater_from_dict,
)
from deeplearning4j_tpu.common.weights import WeightInit, init_weights
from deeplearning4j_tpu.common.distributions import (
    NormalDistribution,
    OrthogonalDistribution,
    TruncatedNormalDistribution,
    UniformDistribution,
    distribution_from_dict,
)


class TestActivations:
    @pytest.mark.parametrize("name", sorted(ACTIVATIONS))
    def test_finite_and_shape(self, name):
        act = get_activation(name)
        x = jnp.linspace(-3, 3, 32).reshape(4, 8)
        y = act(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y)))

    def test_known_values(self):
        x = jnp.array([-1.0, 0.0, 2.0])
        np.testing.assert_allclose(get_activation("relu")(x), [0, 0, 2])
        np.testing.assert_allclose(get_activation("identity")(x), x)
        np.testing.assert_allclose(get_activation("hardtanh")(x), [-1, 0, 1])
        np.testing.assert_allclose(get_activation("cube")(x), [-1, 0, 8])
        sm = get_activation("softmax")(jnp.array([[1.0, 1.0]]))
        np.testing.assert_allclose(sm, [[0.5, 0.5]], atol=1e-6)

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            get_activation("nope")


class TestLosses:
    def test_mse_exact(self):
        loss = LossMSE()
        labels = jnp.array([[1.0, 0.0]])
        preout = jnp.array([[0.5, 0.5]])
        v = loss(labels, preout, get_activation("identity"))
        np.testing.assert_allclose(v, (0.25 + 0.25) / 2, atol=1e-6)

    def test_mcxent_softmax_fused_matches_manual(self):
        loss = LossMCXENT()
        labels = jnp.array([[0.0, 1.0, 0.0]])
        preout = jnp.array([[0.1, 2.0, -1.0]])
        fused = loss(labels, preout, get_activation("softmax"))
        probs = jax.nn.softmax(preout)
        manual = -jnp.log(probs[0, 1])
        np.testing.assert_allclose(fused, manual, rtol=1e-3)

    def test_xent_sigmoid_fused_matches_manual(self):
        loss = LossBinaryXENT()
        labels = jnp.array([[1.0, 0.0]])
        preout = jnp.array([[0.3, -0.2]])
        fused = loss(labels, preout, get_activation("sigmoid"))
        p = jax.nn.sigmoid(preout)
        manual = jnp.sum(-(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p)))
        np.testing.assert_allclose(fused, manual, rtol=1e-5)

    def test_masked_loss(self):
        loss = LossMSE()
        labels = jnp.ones((2, 3))
        preout = jnp.zeros((2, 3))
        mask = jnp.array([1.0, 0.0])
        v = loss(labels, preout, get_activation("identity"), mask=mask)
        np.testing.assert_allclose(v, 1.0, atol=1e-6)  # only first example counts

    def test_serde_roundtrip(self):
        for name in ["mse", "mcxent", "xent", "hinge", "poisson", "kl_divergence"]:
            l = get_loss(name)
            l2 = loss_from_dict(l.to_dict())
            assert type(l2) is type(l)


class TestUpdaters:
    @pytest.mark.parametrize("updater", [
        Sgd(0.1), Adam(0.01), AdaMax(0.01), Nadam(0.01), Nesterovs(0.1, 0.9),
        AdaGrad(0.1), AdaDelta(), RmsProp(0.01), NoOp(),
    ])
    def test_descends_quadratic(self, updater):
        """Each updater should reduce f(x)=||x||² over iterations."""
        x = jnp.array([1.0, -2.0, 3.0])
        state = updater.init_state(x)
        f0 = float(jnp.sum(x * x))
        for it in range(50):
            grad = 2 * x
            delta, state = updater.apply(grad, state, it)
            x = x - delta
        f1 = float(jnp.sum(x * x))
        if isinstance(updater, NoOp):
            assert f1 == f0
        else:
            assert f1 < f0

    def test_sgd_exact(self):
        u = Sgd(0.5)
        delta, _ = u.apply(jnp.array([2.0]), {}, 0)
        np.testing.assert_allclose(delta, [1.0])

    def test_adam_bias_correction_first_step(self):
        u = Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, epsilon=0.0)
        g = jnp.array([0.5])
        delta, _ = u.apply(g, u.init_state(g), 0)
        # first step with bias correction: update ≈ lr * sign(g)
        np.testing.assert_allclose(delta, [1e-3], rtol=1e-5)

    def test_schedule_lr(self):
        u = Sgd(StepSchedule(1.0, 0.1, 10))
        d0, _ = u.apply(jnp.array([1.0]), {}, 0)
        d1, _ = u.apply(jnp.array([1.0]), {}, 15)
        np.testing.assert_allclose(d0, [1.0], rtol=1e-6)
        np.testing.assert_allclose(d1, [0.1], rtol=1e-5)

    def test_serde_roundtrip(self):
        for u in [Sgd(0.1), Adam(0.01, 0.8, 0.95, 1e-9), Nesterovs(0.2, 0.8),
                  RmsProp(0.3), AdaDelta(0.9, 1e-5), NoOp()]:
            u2 = updater_from_dict(u.to_dict())
            assert u2 == u

    def test_schedule_serde_in_updater(self):
        u = Adam(learning_rate=ExponentialSchedule(0.1, 0.99))
        u2 = updater_from_dict(u.to_dict())
        assert isinstance(u2.learning_rate, ExponentialSchedule)
        np.testing.assert_allclose(float(u2.learning_rate.value_at(10)),
                                   float(u.learning_rate.value_at(10)))


class TestSchedules:
    def test_values(self):
        assert float(FixedSchedule(0.5).value_at(100)) == 0.5
        np.testing.assert_allclose(float(ExponentialSchedule(1.0, 0.5).value_at(2)), 0.25)
        np.testing.assert_allclose(float(StepSchedule(1.0, 0.5, 10).value_at(25)), 0.25)
        m = MapSchedule({0: 1.0, 10: 0.1, 20: 0.01})
        np.testing.assert_allclose(float(m.value_at(5)), 1.0)
        np.testing.assert_allclose(float(m.value_at(15)), 0.1)
        np.testing.assert_allclose(float(m.value_at(99)), 0.01)

    def test_warmup_cosine(self):
        s = WarmupCosineSchedule(1.0, 10, 100)
        assert float(s.value_at(0)) == 0.0
        np.testing.assert_allclose(float(s.value_at(10)), 1.0, atol=1e-6)
        np.testing.assert_allclose(float(s.value_at(100)), 0.0, atol=1e-6)

    def test_serde(self):
        for s in [FixedSchedule(0.1), ExponentialSchedule(1, 0.9),
                  StepSchedule(1, 0.5, 7), MapSchedule({0: 1.0, 5: 0.5}),
                  WarmupCosineSchedule(0.1, 5, 50)]:
            s2 = schedule_from_dict(s.to_dict())
            np.testing.assert_allclose(float(s2.value_at(7)), float(s.value_at(7)))


class TestWeightInit:
    def test_variances(self):
        rng = jax.random.PRNGKey(0)
        n_in, n_out = 400, 300
        w = init_weights(rng, (n_in, n_out), WeightInit.XAVIER, n_in, n_out)
        np.testing.assert_allclose(float(jnp.var(w)), 2.0 / (n_in + n_out), rtol=0.1)
        w = init_weights(rng, (n_in, n_out), WeightInit.RELU, n_in, n_out)
        np.testing.assert_allclose(float(jnp.var(w)), 2.0 / n_in, rtol=0.1)
        w = init_weights(rng, (n_in, n_out), WeightInit.LECUN_NORMAL, n_in, n_out)
        np.testing.assert_allclose(float(jnp.var(w)), 1.0 / n_in, rtol=0.1)

    def test_special(self):
        rng = jax.random.PRNGKey(0)
        assert float(jnp.sum(init_weights(rng, (3, 4), WeightInit.ZERO, 3, 4))) == 0
        assert float(jnp.sum(init_weights(rng, (3, 4), WeightInit.ONES, 3, 4))) == 12
        np.testing.assert_allclose(init_weights(rng, (3, 3), WeightInit.IDENTITY, 3, 3),
                                   jnp.eye(3))

    def test_uniform_bounds(self):
        rng = jax.random.PRNGKey(1)
        w = init_weights(rng, (100, 100), WeightInit.XAVIER_UNIFORM, 100, 100)
        bound = np.sqrt(6.0 / 200)
        assert float(jnp.max(jnp.abs(w))) <= bound + 1e-6


class TestDistributions:
    def test_normal(self):
        d = NormalDistribution(2.0, 0.5)
        s = d.sample(jax.random.PRNGKey(0), (10000,))
        np.testing.assert_allclose(float(jnp.mean(s)), 2.0, atol=0.05)
        np.testing.assert_allclose(float(jnp.std(s)), 0.5, atol=0.05)

    def test_uniform(self):
        d = UniformDistribution(-2, 3)
        s = d.sample(jax.random.PRNGKey(0), (1000,))
        assert float(jnp.min(s)) >= -2 and float(jnp.max(s)) <= 3

    def test_truncated(self):
        d = TruncatedNormalDistribution(0.0, 1.0)
        s = d.sample(jax.random.PRNGKey(0), (1000,))
        assert float(jnp.max(jnp.abs(s))) <= 2.0 + 1e-5

    def test_orthogonal(self):
        d = OrthogonalDistribution()
        s = d.sample(jax.random.PRNGKey(0), (16, 16))
        np.testing.assert_allclose(np.asarray(s @ s.T), np.eye(16), atol=1e-2)

    def test_serde(self):
        d = NormalDistribution(1.0, 2.0)
        d2 = distribution_from_dict(d.to_dict())
        assert d2 == d


class TestCompilationCache:
    def test_enable_populates_cache_dir(self, tmp_path):
        """enable_compilation_cache points JAX's persistent cache at the
        dir; a fresh jitted program writes an entry there."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nd import enable_compilation_cache

        import os

        from jax._src import compilation_cache as _cc

        # conftest already bound the persistent-cache singleton to the
        # suite-wide dir; re-pointing the config only takes effect
        # after a reset
        _cc.reset_cache()
        d = enable_compilation_cache(tmp_path / "xla", min_compile_time_secs=0)
        try:
            @jax.jit
            def f(a, b):
                return jnp.tanh(a @ b) + a.sum()

            f(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
            assert os.path.isdir(d)
            assert len(os.listdir(d)) >= 1, "no cache entry written"
        finally:
            # restore the suite-wide cache for later tests
            _cc.reset_cache()
            enable_compilation_cache(
                os.environ.get("DL4J_TEST_XLA_CACHE",
                               os.path.expanduser("~/.cache/dl4tpu-xla-tests")),
                min_compile_time_secs=0.5)
