"""Evaluation reporting-surface depth (reference `eval/Evaluation.java`
1,627 LoC: per-class stat tables :499-509, FPR/FNR/falseAlarm
:851-975, fBeta/gMeasure :998-1106, MACRO/MICRO averaging, count maps
:1218-1262, JSON serde, merge :1392) and the mesh-wide evaluate path
(reference `spark/impl/multilayer/scoring/`)."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.eval.evaluation import EvaluationAveraging


def _mk_eval(labels=None):
    ev = Evaluation(3, labels_names=labels)
    y = np.eye(3)[[0, 0, 0, 0, 1, 1, 1, 2, 2, 2]]
    # predictions: class0 4/4; class1 2/3 (one → 0); class2 1/3 (two → 1)
    p = np.eye(3)[[0, 0, 0, 0, 1, 1, 0, 2, 1, 1]] * 0.9 + 0.05
    ev.eval(y, p)
    return ev


class TestRates:
    def test_fpr_fnr_per_class(self):
        ev = _mk_eval()
        # class 0: FP=2 (1 from c1, 1... actually c1→0 once), TN: check
        fp, tn = ev.false_positives(), ev.true_negatives()
        for c in range(3):
            denom = fp[c] + tn[c]
            assert ev.false_positive_rate(c) == pytest.approx(
                fp[c] / denom if denom else 0.0)
        fn, tp = ev.false_negatives(), ev.true_positives()
        for c in range(3):
            denom = fn[c] + tp[c]
            assert ev.false_negative_rate(c) == pytest.approx(
                fn[c] / denom if denom else 0.0)

    def test_false_alarm_rate_is_mean_of_avg_rates(self):
        ev = _mk_eval()
        want = (ev.false_positive_rate() + ev.false_negative_rate()) / 2
        assert ev.false_alarm_rate() == pytest.approx(want)

    def test_positive_negative_counts(self):
        ev = _mk_eval()
        assert ev.positive() == {0: 4, 1: 3, 2: 3}
        assert ev.negative() == {0: 6, 1: 7, 2: 7}
        assert ev.class_count(0) == 4
        assert ev.get_num_row_counter() == 10


class TestAveraging:
    def test_micro_precision_recall_equal_accuracy_single_label(self):
        # single-label multiclass: micro-P == micro-R == accuracy
        ev = _mk_eval()
        for m in (ev.precision, ev.recall):
            assert m(averaging=EvaluationAveraging.MICRO) == pytest.approx(
                ev.accuracy())

    def test_macro_micro_diverge_on_imbalance(self):
        ev = _mk_eval()
        assert (ev.recall(averaging="macro")
                != pytest.approx(ev.recall(averaging="micro")))

    def test_fbeta_beta1_matches_f1(self):
        ev = _mk_eval()
        for c in range(3):
            assert ev.f_beta(1.0, c) == pytest.approx(ev.f1(c))

    def test_fbeta_beta2_weights_recall(self):
        ev = _mk_eval()
        # class 2 has P=1.0, R=1/3 → beta=2 should sit closer to R
        f2 = ev.f_beta(2.0, 2)
        assert ev.recall(2) < f2 < ev.precision(2)
        assert abs(f2 - ev.recall(2)) < abs(f2 - ev.precision(2))

    def test_gmeasure_macro(self):
        ev = _mk_eval()
        want = np.mean([ev.gmeasure(i) for i in range(3)])
        assert ev.gmeasure() == pytest.approx(want)

    def test_matthews_macro(self):
        ev = _mk_eval()
        want = np.mean([ev.matthews_correlation(i) for i in range(3)])
        assert ev.matthews_correlation() == pytest.approx(want)

    def test_matthews_micro_uses_summed_counts(self):
        ev = _mk_eval()
        tp = sum(ev.true_positives().values())
        fp = sum(ev.false_positives().values())
        fn = sum(ev.false_negatives().values())
        tn = sum(ev.true_negatives().values())
        want = (tp * tn - fp * fn) / np.sqrt(
            float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        got = ev.matthews_correlation(averaging=EvaluationAveraging.MICRO)
        assert got == pytest.approx(want)
        assert got != pytest.approx(ev.matthews_correlation())


class TestStatsReport:
    def test_label_names_in_per_class_table(self):
        ev = _mk_eval(labels=["cat", "dog", "bird"])
        s = ev.stats()
        assert "cat" in s and "dog" in s and "bird" in s
        assert "FPR" in s and "FNR" in s

    def test_warning_surfaced_for_never_predicted_class(self):
        ev = Evaluation(3, labels_names=["a", "b", "c"])
        y = np.eye(3)[[0, 1, 0, 1]]
        p = np.eye(3)[[0, 1, 0, 0]]
        ev.eval(y, p)
        s = ev.stats()
        assert "Warning" in s and "c" in s
        assert "Warning" not in ev.stats(suppress_warnings=True)

    def test_get_class_label_fallback(self):
        ev = _mk_eval()
        assert ev.get_class_label(1) == "1"


class TestSerde:
    def test_json_round_trip_preserves_all_metrics(self):
        ev = _mk_eval(labels=["x", "y", "z"])
        ev2 = Evaluation.from_json(ev.to_json())
        assert ev2.accuracy() == pytest.approx(ev.accuracy())
        assert ev2.f1() == pytest.approx(ev.f1())
        for c in range(3):
            assert ev2.precision(c) == pytest.approx(ev.precision(c))
            assert ev2.false_positive_rate(c) == pytest.approx(
                ev.false_positive_rate(c))
        assert ev2.labels_names == ["x", "y", "z"]
        np.testing.assert_array_equal(ev2.confusion.matrix,
                                      ev.confusion.matrix)

    def test_from_json_rejects_wrong_type(self):
        with pytest.raises(ValueError, match=r"Not a\(n\) Evaluation"):
            Evaluation.from_json('{"type": "ROC"}')


class TestCtorsAndReset:
    def test_labels_list_ctor(self):
        ev = Evaluation(["a", "b"])
        assert ev.num_classes == 2 and ev.labels_names == ["a", "b"]

    def test_binary_decision_threshold(self):
        ev = Evaluation(2, binary_decision_threshold=0.9)
        y = np.eye(2)[[1, 1]]
        p = np.array([[0.2, 0.8], [0.05, 0.95]])
        ev.eval(y, p)  # 0.8 < 0.9 → class 0; 0.95 ≥ 0.9 → class 1
        assert ev.accuracy() == pytest.approx(0.5)

    def test_cost_array_reweights_argmax(self):
        ev = Evaluation(2, cost_array=[1.0, 10.0])
        y = np.eye(2)[[0]]
        p = np.array([[0.6, 0.4]])  # cost-scaled: 0.6 vs 4.0 → class 1
        ev.eval(y, p)
        assert ev.accuracy() == 0.0

    def test_eval_single_and_reset(self):
        ev = Evaluation(2)
        ev.eval_single(0, 0)
        ev.eval_single(1, 0)
        assert ev.accuracy() == pytest.approx(0.5)
        ev.reset()
        assert ev.total == 0 and ev.confusion is None


class TestReferenceAccessorParity:
    """Every public accessor of `Evaluation.java` :461-1423 maps to an
    equivalent here or has a documented skip — the VERDICT's asked-for
    enumeration."""

    PARITY = {
        "eval(INDArray,INDArray)": "eval",
        "eval(int,int)": "eval_single",
        "stats()/stats(suppressWarnings)": "stats",
        "precision(cls)/precision()/precision(averaging)": "precision",
        "recall(cls)/recall()/recall(averaging)": "recall",
        "falsePositiveRate(...)": "false_positive_rate",
        "falseNegativeRate(...)": "false_negative_rate",
        "falseAlarmRate()": "false_alarm_rate",
        "f1(...)": "f1",
        "fBeta(beta,...)": "f_beta",
        "gMeasure(...)": "gmeasure",
        "accuracy()": "accuracy",
        "topNAccuracy()": "top_n_accuracy",
        "matthewsCorrelation(...)": "matthews_correlation",
        "truePositives()": "true_positives",
        "trueNegatives()": "true_negatives",
        "falsePositives()": "false_positives",
        "falseNegatives()": "false_negatives",
        "positive()": "positive",
        "negative()": "negative",
        "classCount(cls)": "class_count",
        "getNumRowCounter()": "get_num_row_counter",
        "getClassLabel(cls)": "get_class_label",
        "getConfusionMatrix()": "confusion",
        "merge(other)": "merge",
        "reset()": "reset",
        "getPredictionErrors()": "get_prediction_errors",
        "getPredictionsByActualClass()": "get_predictions_by_actual_class",
        "getPredictionsByPredictedClass()":
            "get_predictions_by_predicted_class",
        "getPredictions(a,p)": "get_predictions",
        "toJson/fromJson": "to_json",
    }
    # documented skips: incrementTruePositives etc. (:1295-1307) mutate
    # raw counters without a confusion entry — internal bookkeeping the
    # confusion-matrix design makes unrepresentable; averageXNumClasses-
    # Excluded (:711-741) exposes the edge-case-exclusion count of the
    # DEFAULT averaging, visible here via warnings() instead.

    def test_every_mapped_accessor_exists(self):
        ev = _mk_eval()
        for ref, ours in self.PARITY.items():
            assert hasattr(ev, ours), f"{ref} → missing {ours}"


class TestNInResolution:
    def test_first_layer_n_in_seeds_ff_chain(self):
        """DL4J-style config: nIn only on the first layer, no input
        type — later layers' widths must chain-resolve."""
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert net.params["1"]["W"].shape == (16, 3)

    def test_unresolved_width_fails_at_init(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu"))  # no n_in
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        with pytest.raises(ValueError, match="input width unresolved"):
            MultiLayerNetwork(conf).init()


class TestMeshEvaluate:
    def test_parallel_trainer_evaluate_matches_host(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]

        tr = ParallelTrainer(net)
        ev = tr.evaluate(x, y, batch_size=16)
        # host-side oracle
        ev_host = Evaluation()
        ev_host.eval(y, np.asarray(net.output(x)))
        assert ev.total == 64
        assert ev.accuracy() == pytest.approx(ev_host.accuracy())
        np.testing.assert_array_equal(ev.confusion.matrix,
                                      ev_host.confusion.matrix)

    def test_evaluate_scores_ragged_tail(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((37, 4)).astype(np.float32)  # ragged vs 8
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 37)]
        ev = ParallelTrainer(net).evaluate(x, y, batch_size=16)
        assert ev.total == 37  # no example silently skipped


class TestRocRegressionSerde:
    def test_roc_json_round_trip_and_merge(self):
        from deeplearning4j_tpu.eval.roc import ROC
        rng = np.random.default_rng(3)
        y = (rng.random(100) > 0.5).astype(np.float64)
        p = np.clip(y * 0.6 + rng.random(100) * 0.4, 0, 1)
        roc = ROC()
        roc.eval(y[:50], p[:50])
        roc2 = ROC()
        roc2.eval(y[50:], p[50:])
        merged = ROC().merge(roc).merge(roc2)
        full = ROC()
        full.eval(y, p)
        assert merged.calculate_auc() == pytest.approx(full.calculate_auc())
        rt = ROC.from_json(full.to_json())
        assert rt.calculate_auc() == pytest.approx(full.calculate_auc())

    def test_regression_json_round_trip_and_merge(self):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        rng = np.random.default_rng(4)
        y = rng.standard_normal((60, 3))
        p = y + 0.1 * rng.standard_normal((60, 3))
        a, b, full = (RegressionEvaluation() for _ in range(3))
        a.eval(y[:30], p[:30])
        b.eval(y[30:], p[30:])
        a.merge(b)
        full.eval(y, p)
        for c in range(3):
            assert a.mean_squared_error(c) == pytest.approx(
                full.mean_squared_error(c))
        rt = RegressionEvaluation.from_json(full.to_json())
        for c in range(3):
            assert rt.correlation_r2(c) == pytest.approx(
                full.correlation_r2(c))


class TestBinaryCalibrationSerde:
    def test_binary_merge_and_round_trip(self):
        from deeplearning4j_tpu.eval.binary import EvaluationBinary
        rng = np.random.default_rng(0)
        y = (rng.random((40, 3)) > 0.5).astype(np.float64)
        p = rng.random((40, 3))
        a, b, full = (EvaluationBinary() for _ in range(3))
        a.eval(y[:20], p[:20])
        b.eval(y[20:], p[20:])
        a.merge(b)
        full.eval(y, p)
        for c in range(3):
            assert a.f1(c) == pytest.approx(full.f1(c))
        rt = EvaluationBinary.from_json(full.to_json())
        for c in range(3):
            assert rt.precision(c) == pytest.approx(full.precision(c))
        other = EvaluationBinary(threshold=0.7)
        other.eval(y, p)
        with pytest.raises(ValueError, match="threshold"):
            full.merge(other)

    def test_calibration_merge_and_round_trip(self):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        rng = np.random.default_rng(1)
        y = np.eye(3)[rng.integers(0, 3, 40)]
        p = rng.random((40, 3))
        a, b, full = (EvaluationCalibration() for _ in range(3))
        a.eval(y[:20], p[:20])
        b.eval(y[20:], p[20:])
        a.merge(b)
        full.eval(y, p)
        np.testing.assert_array_equal(a._bin_counts, full._bin_counts)
        np.testing.assert_array_equal(a._residual_hist, full._residual_hist)
        rt = EvaluationCalibration.from_json(full.to_json())
        np.testing.assert_array_equal(rt._bin_counts, full._bin_counts)
        np.testing.assert_array_equal(rt._residual_hist, full._residual_hist)
        rt.eval(y, p)  # round-tripped object must keep accumulating
        with pytest.raises(ValueError, match="different bins"):
            EvaluationCalibration(reliability_bins=5).merge(full)


class TestMeshEvaluateRegression:
    def test_parallel_trainer_evaluate_accepts_regression_evaluator(self):
        """evaluate() is evaluator-generic: passing a
        RegressionEvaluation scores regression outputs over the mesh."""
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        conf = (NeuralNetConfiguration.builder().seed(4).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=6, n_out=12, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="identity",
                                   loss="mse"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(5)
        x = rng.standard_normal((64, 6)).astype(np.float32)
        y = rng.standard_normal((64, 2)).astype(np.float32)
        ev = ParallelTrainer(net).evaluate(
            x, y, batch_size=16, evaluation=RegressionEvaluation())
        host = RegressionEvaluation()
        host.eval(y, np.asarray(net.output(x)))
        for c in range(2):
            assert ev.mean_squared_error(c) == pytest.approx(
                host.mean_squared_error(c), rel=1e-6)


class TestRocFamilySerde:
    @pytest.mark.parametrize("cls_name", ["ROCBinary", "ROCMultiClass"])
    def test_merge_and_round_trip(self, cls_name):
        import deeplearning4j_tpu.eval.roc as roc_mod
        cls = getattr(roc_mod, cls_name)
        rng = np.random.default_rng(6)
        y = np.eye(3)[rng.integers(0, 3, 60)]
        p = rng.random((60, 3))
        a, b, full = cls(), cls(), cls()
        a.eval(y[:30], p[:30])
        b.eval(y[30:], p[30:])
        a.merge(b)
        full.eval(y, p)
        for c in range(3):
            assert a.calculate_auc(c) == pytest.approx(full.calculate_auc(c))
        rt = cls.from_json(full.to_json())
        assert rt.calculate_auc(1) == pytest.approx(full.calculate_auc(1))

    def test_column_count_mismatch_rejected(self):
        from deeplearning4j_tpu.eval.roc import ROCBinary
        a, b = ROCBinary(), ROCBinary()
        a.eval(np.eye(2)[[0, 1]], np.random.rand(2, 2))
        b.eval(np.eye(3)[[0, 1]], np.random.rand(2, 3))
        with pytest.raises(ValueError, match="column counts"):
            a.merge(b)


class TestContainerEvaluateOverloads:
    """Container-level evaluate overloads (reference
    `MultiLayerNetwork.evaluate(iterator, labelsList, topN)` :2892-2944,
    `evaluateROC` :2814, `evaluateROCMultiClass` :2825)."""

    def _net(self, n_out=3):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=n_out, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n_out=3, n=48):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((n, 4)).astype(np.float32)
        y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
        from deeplearning4j_tpu.datasets import DataSet
        return DataSet(x, y)

    def test_evaluate_labels_and_topn(self):
        net = self._net()
        ev = net.evaluate(self._data(), labels_list=["a", "b", "c"], top_n=2)
        assert "a" in ev.stats()
        assert ev.top_n_accuracy() >= ev.accuracy()

    def test_evaluate_roc_binary(self):
        net = self._net(n_out=2)
        roc = net.evaluate_roc(self._data(n_out=2))
        auc = roc.calculate_auc()
        assert 0.0 <= auc <= 1.0

    def test_evaluate_roc_multi_class(self):
        net = self._net()
        roc = net.evaluate_roc_multi_class(self._data())
        assert 0.0 <= roc.calculate_average_auc() <= 1.0

    def test_graph_evaluate_overloads(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration, ComputationGraph
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

        b = NeuralNetConfiguration.builder().updater(Adam(1e-2))
        g = ComputationGraphConfiguration.graph_builder(b)
        g.add_inputs("in")
        g.add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d")
        g.set_input_types(InputType.feed_forward(4))
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        ds = self._data()
        ev = net.evaluate(ds, labels_list=["x", "y", "z"], top_n=2)
        assert "x" in ev.stats()
        roc = net.evaluate_roc_multi_class(ds)
        assert 0.0 <= roc.calculate_average_auc() <= 1.0
