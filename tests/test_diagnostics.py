"""In-graph model-internals diagnostics (monitor/diagnostics.py).

Contracts under test (ISSUE 8 acceptance criteria):
- trajectory BIT-parity diagnostics-on vs -off: plain, fused spe=3,
  scan_layers deep stacks, mixed_bf16, threshold gradient sharing,
  graph container — watchdog "warn" included;
- packed-run per-layer keying: stats are keyed per layer and agree
  whether or not the run executes as a `lax.scan` (scan-config
  independence, like checkpoints);
- watchdog policies: warn counts + logs, skip discards the bad update
  in-graph and counts it, halt raises NonFiniteGradientsError naming
  the offending layers;
- transfer contract: at listener cadence the stats arrive in ≤1
  batched d2h transfer (asserted on the jax_transfers_total counter),
  off-cadence steps add ZERO transfers;
- resolution/serde: DL4J_DIAGNOSTICS env > arg > conf, config
  round-trips through both configurations' serde.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.monitor.diagnostics import (
    DiagnosticsConfig,
    NonFiniteGradientsError,
    as_diagnostics,
    resolve_diagnostics,
)
from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _mlp_conf(depth=3, diagnostics=None, scan_layers=True, policy=None,
              updater=None):
    b = NeuralNetConfiguration.builder().seed(7)
    if updater is not None:
        b = b.updater(updater)
    if policy is not None:
        b = b.dtype_policy(policy)
    lb = b.list()
    for _ in range(depth):
        lb = lb.layer(DenseLayer(n_in=8, n_out=8, activation="tanh"))
    lb = lb.layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss="mcxent"))
    lb = lb.scan_layers(scan_layers)
    if diagnostics is not None:
        lb = lb.diagnostics(diagnostics)
    return lb.build()


def _net(**kw):
    return MultiLayerNetwork(_mlp_conf(**kw)).init()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def _bit_equal(a, b):
    la = jax.tree_util.tree_leaves(a.params)
    lb = jax.tree_util.tree_leaves(b.params)
    return all(np.array_equal(np.asarray(u), np.asarray(v))
               for u, v in zip(la, lb))


class TestBitParity:
    """Enabling diagnostics must not move a single bit of the
    trajectory (aux outputs only) — including watchdog 'warn'."""

    def test_plain(self):
        x, y = _data()
        off = _net()
        off.fit(x, y, epochs=2, batch_size=8, shuffle=False)
        on = _net(diagnostics="warn")
        on.fit(x, y, epochs=2, batch_size=8, shuffle=False)
        assert _bit_equal(off, on)
        d = on._last_diagnostics
        assert set(d["params"]["0_W"]) == {
            "grad_mm", "grad_l2", "upd_mm", "upd_l2", "param_mm",
            "param_l2", "ratio"}
        assert not d["nonfinite"]

    def test_fused_spe3(self):
        x, y = _data(48)
        off = _net()
        off.fit(x, y, epochs=2, batch_size=8, shuffle=False,
                steps_per_execution=3)
        on = _net(diagnostics="warn")
        on.fit(x, y, epochs=2, batch_size=8, shuffle=False,
               steps_per_execution=3)
        assert _bit_equal(off, on)
        assert on._last_diagnostics is not None

    def test_scan_deep_stack(self):
        x, y = _data()
        off = _net(depth=6)
        off.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        on = _net(depth=6, diagnostics=True)
        on.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        assert _bit_equal(off, on)
        # per-layer keys despite the stacked:: packed run
        assert {f"{i}_W" for i in range(6)} <= set(
            on._last_diagnostics["params"])

    def test_mixed_bf16(self):
        x, y = _data()
        off = _net(policy="mixed_bf16")
        off.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        on = _net(policy="mixed_bf16", diagnostics="warn")
        on.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        assert _bit_equal(off, on)
        # stats computed fp32 (host dicts are python floats; the check
        # is that values are finite and sane, not bf16-quantized zeros)
        st = on._last_diagnostics["params"]["0_W"]
        assert st["grad_l2"] > 0 and np.isfinite(st["ratio"])

    def test_graph_container(self):
        def build(diag=None):
            gb = (ComputationGraphConfiguration.graph_builder(
                NeuralNetConfiguration.builder().seed(3))
                .add_inputs("in"))
            prev = "in"
            for i in range(3):
                gb.add_layer(f"d{i}",
                             DenseLayer(n_in=8, n_out=8,
                                        activation="tanh"), prev)
                prev = f"d{i}"
            gb.add_layer("out", OutputLayer(n_in=8, n_out=3,
                                            activation="softmax",
                                            loss="mcxent"), prev)
            gb.set_outputs("out")
            if diag is not None:
                gb.diagnostics(diag)
            return ComputationGraph(gb.build()).init()

        x, y = _data()
        off = build()
        off.fit(x, y, epochs=1, batch_size=8, steps_per_execution=2)
        on = build("warn")
        on.fit(x, y, epochs=1, batch_size=8, steps_per_execution=2)
        assert _bit_equal(off, on)
        assert "d1_W" in on._last_diagnostics["params"]
        assert "d0" in on._last_diagnostics["activations"]

    def test_threshold_gradient_sharing(self):
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        x, y = _data(64)
        off = _net(updater=Adam(0.01))
        ParallelTrainer(off, device_mesh(), mode="sync",
                        gradient_sharing="threshold").fit(
            x, y, epochs=1, batch_size=16, steps_per_execution=2)
        on = _net(updater=Adam(0.01), diagnostics="warn")
        ParallelTrainer(on, device_mesh(), mode="sync",
                        gradient_sharing="threshold").fit(
            x, y, epochs=1, batch_size=16, steps_per_execution=2)
        assert _bit_equal(off, on)
        # exchange-path stats: POST-exchange updates (no grad stats —
        # gradients live inside the VJP hooks)
        st = on._last_diagnostics["params"]["0_W"]
        assert "upd_mm" in st and "grad_mm" not in st


class TestPackedRunKeying:
    """Per-layer stats must be independent of the scan configuration
    (axis-0 reductions over the packed run — never unpacked)."""

    def test_scan_on_off_same_keys_same_values(self):
        x, y = _data()
        scan = _net(depth=5, diagnostics=True, scan_layers=True)
        scan.fit(x, y, epochs=1, batch_size=32, shuffle=False)
        unrolled = _net(depth=5, diagnostics=True, scan_layers=False)
        unrolled.fit(x, y, epochs=1, batch_size=32, shuffle=False)
        ds, du = scan._last_diagnostics, unrolled._last_diagnostics
        assert set(ds["params"]) == set(du["params"])
        for key in ds["params"]:
            for st in ds["params"][key]:
                np.testing.assert_allclose(
                    ds["params"][key][st], du["params"][key][st],
                    rtol=2e-4, atol=1e-7, err_msg=f"{key}.{st}")
        assert set(ds["activations"]) == set(du["activations"])
        for lk in ds["activations"]:
            for st in ds["activations"][lk]:
                np.testing.assert_allclose(
                    ds["activations"][lk][st],
                    du["activations"][lk][st], rtol=2e-4, atol=1e-7)


class TestWatchdog:
    def _poisoned(self):
        x, y = _data(24)
        xb = x.copy()
        xb[8:16] = np.inf  # second batch non-finite
        return xb, y

    def test_warn_counts_and_preserves_trajectory(self):
        xb, y = self._poisoned()
        plain = _net()
        plain.fit(xb, y, epochs=1, batch_size=8, shuffle=False)
        warn = _net(diagnostics="warn")
        warn.fit(xb, y, epochs=1, batch_size=8, shuffle=False)
        # warn never touches the update — trajectories match even
        # through the non-finite region (NaN == NaN positionally)
        for u, v in zip(jax.tree_util.tree_leaves(plain.params),
                        jax.tree_util.tree_leaves(warn.params)):
            assert np.array_equal(np.asarray(u), np.asarray(v),
                                  equal_nan=True)
        assert warn._diag.nonfinite_total >= 1
        assert warn._diag.skipped_total == 0

    def test_skip_discards_in_graph_and_counts(self):
        xb, y = self._poisoned()
        net = _net(diagnostics="skip")
        net.fit(xb, y, epochs=1, batch_size=8, shuffle=False)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(net.params))
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(net.updater_state))
        assert net._diag.skipped_total == 1
        assert net._diag.nonfinite_total == 1

    def test_skip_healthy_trajectory_bit_identical(self):
        x, y = _data()
        off = _net()
        off.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        on = _net(diagnostics="skip")
        on.fit(x, y, epochs=1, batch_size=8, shuffle=False)
        assert _bit_equal(off, on)
        assert on._diag.skipped_total == 0

    def test_halt_raises_with_layer_keys(self):
        xb, y = self._poisoned()
        net = _net(diagnostics="halt")
        with pytest.raises(NonFiniteGradientsError) as ei:
            net.fit(xb, y, epochs=1, batch_size=8, shuffle=False)
        assert ei.value.iteration == 1
        assert ei.value.layer_keys  # offending layers named

    def test_skip_in_fused_group(self):
        xb, y = self._poisoned()
        net = _net(diagnostics="skip")
        net.fit(xb, y, epochs=1, batch_size=8, shuffle=False,
                steps_per_execution=3)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(net.params))
        assert net._diag.skipped_total == 1

    def test_watchdog_registry_counters(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            xb, y = self._poisoned()
            net = _net(diagnostics="skip")
            net.fit(xb, y, epochs=1, batch_size=8, shuffle=False)
            assert reg.counter("watchdog_nonfinite_total").value == 1
            assert reg.counter("watchdog_skipped_total").value == 1
            assert "watchdog_nonfinite_total 1" in reg.exposition()
        finally:
            monitor.disable()


class TestTransferContract:
    """≤1 batched d2h transfer per report cadence; zero off-cadence."""

    def _d2h(self, reg):
        return reg.counter("jax_transfers_total", direction="d2h").value

    def test_per_step_cadence(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            x, y = _data()
            cfg = DiagnosticsConfig(report_frequency=2)
            net = _net(diagnostics=cfg)
            before = self._d2h(reg)
            net.fit(x, y, epochs=1, batch_size=8, shuffle=False)  # 4 its
            # iterations 0 and 2 are on cadence -> exactly 2 transfers
            assert self._d2h(reg) - before == 2
        finally:
            monitor.disable()

    def test_fused_group_single_transfer(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            x, y = _data(48)
            net = _net(diagnostics=True)
            before = self._d2h(reg)
            # 6 iterations in 2 fused groups -> 2 batched transfers
            net.fit(x, y, epochs=1, batch_size=8, shuffle=False,
                    steps_per_execution=3)
            assert self._d2h(reg) - before == 2
        finally:
            monitor.disable()

    def test_disabled_zero_transfers(self):
        reg = MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            x, y = _data()
            net = _net()
            before = self._d2h(reg)
            net.fit(x, y, epochs=1, batch_size=8, shuffle=False)
            assert self._d2h(reg) - before == 0
        finally:
            monitor.disable()


class TestResolutionAndSerde:
    def test_as_diagnostics_forms(self):
        assert as_diagnostics(None) is None
        assert as_diagnostics(False) is None
        assert as_diagnostics("off") is None
        assert as_diagnostics(True) == DiagnosticsConfig()
        assert as_diagnostics("skip").watchdog == "skip"
        cfg = DiagnosticsConfig(histograms=True)
        assert as_diagnostics(cfg) is cfg
        assert as_diagnostics(cfg.to_dict()) == cfg
        with pytest.raises(ValueError):
            as_diagnostics("bogus")
        with pytest.raises(ValueError):
            DiagnosticsConfig(watchdog="explode")

    def test_env_overrides(self, monkeypatch):
        conf = _mlp_conf(diagnostics="warn")
        assert resolve_diagnostics(None, conf).watchdog == "warn"
        monkeypatch.setenv("DL4J_DIAGNOSTICS", "0")
        assert resolve_diagnostics("skip", conf) is None
        monkeypatch.setenv("DL4J_DIAGNOSTICS", "halt")
        assert resolve_diagnostics(None, conf).watchdog == "halt"
        monkeypatch.setenv("DL4J_DIAGNOSTICS", "sideways")
        with pytest.raises(ValueError):
            resolve_diagnostics(None, conf)

    def test_arg_beats_conf(self):
        conf = _mlp_conf(diagnostics="warn")
        net = MultiLayerNetwork(conf, diagnostics="skip")
        assert net.diagnostics.watchdog == "skip"
        net2 = MultiLayerNetwork(conf)
        assert net2.diagnostics.watchdog == "warn"

    def test_mlc_serde_roundtrip(self):
        conf = _mlp_conf(diagnostics=DiagnosticsConfig(
            watchdog="skip", histograms=True, report_frequency=5))
        rt = MultiLayerConfiguration.from_dict(conf.to_dict())
        assert rt.diagnostics == conf.diagnostics
        plain = _mlp_conf()
        assert MultiLayerConfiguration.from_dict(
            plain.to_dict()).diagnostics is None

    def test_graph_serde_roundtrip(self):
        gb = (ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder())
            .add_inputs("in"))
        gb.add_layer("d", DenseLayer(n_in=4, n_out=4), "in")
        gb.add_layer("out", OutputLayer(n_in=4, n_out=2), "d")
        gb.set_outputs("out").diagnostics("halt")
        conf = gb.build()
        rt = ComputationGraphConfiguration.from_dict(conf.to_dict())
        assert rt.diagnostics.watchdog == "halt"

    def test_checkpoint_meta_preserves_active_config(self):
        # an ARG-selected watchdog (not in the conf) must survive
        # fault-runtime resume — under `skip` it is trajectory-bearing
        from deeplearning4j_tpu.fault import state as fs
        net = MultiLayerNetwork(_mlp_conf(), diagnostics="skip").init()
        snap = fs.capture_training_state(net)
        rebuilt = fs.build_model(snap["meta"])
        assert rebuilt.diagnostics.watchdog == "skip"
        plain = MultiLayerNetwork(_mlp_conf()).init()
        snap2 = fs.capture_training_state(plain)
        assert fs.build_model(snap2["meta"]).diagnostics is None

    def test_histograms_in_aux(self):
        x, y = _data()
        cfg = DiagnosticsConfig(histograms=True, histogram_bins=8,
                                histogram_range=2.0)
        net = _net(diagnostics=cfg)
        net.fit(x, y, epochs=1, batch_size=32, shuffle=False)
        h = net._last_diagnostics["hists"]["0_W"]
        assert len(h) == 8
        assert float(np.sum(h)) == 64.0  # every 8x8 weight counted
