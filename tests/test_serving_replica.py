"""Horizontal serving: replica fan-out, least-loaded routing,
disaggregated prefill/decode, request migration.

The spine of this suite is the CROSS-PROCESS parity contract
(docs/SERVING.md "Horizontal serving"): a stream served by any replica
of a model — including one handed off prefill→decode over the `DLFP`
frame, or migrated mid-flood off a killed replica — finishes bit-equal
to the single-process reference. Plus the wire-hardening contract:
every malformed frame decodes to one typed `WireFormatError`, never a
leaked `struct.error`/`KeyError`.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.elastic import (
    ElasticCoordinator,
    serving_directory,
)
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.disagg import (
    DecodeWorker,
    PrefillWorker,
    run_disaggregated,
)
from deeplearning4j_tpu.serving.replica import (
    ReplicaClient,
    ReplicaLostError,
    ReplicaManager,
    ReplicaSet,
    ReplicaWorker,
)
from deeplearning4j_tpu.serving.router import FleetRouter, MigratingStream
from deeplearning4j_tpu.serving.server import GenerationServer, ShedError
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 48
N_TOK = 8


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net():
    return tiny_lm()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 4))


@pytest.fixture(scope="module")
def ref(net, prompts):
    return generate(net, prompts, N_TOK, temperature=0)


@pytest.fixture()
def coord():
    c = ElasticCoordinator(settle_s=0.05, grace_s=1.0,
                           tick_s=0.05).start()
    yield c
    c.stop()


def _worker(net, addr, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("n_blocks", 64)
    kw.setdefault("block_len", 4)
    return ReplicaWorker(net, model="m", version=1, coordinator=addr,
                         heartbeat_interval_s=0.05, **kw).start()


def _wait_replicas(rset, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rset.refresh(force=True)
        if len(rset.backends()) == n:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"replica set never reached {n} backends "
        f"({len(rset.backends())} live)")


# ===================================================== wire hardening
class TestWireHardening:
    def test_request_roundtrip(self):
        rng = np.asarray([3, 5], np.uint32)
        frame = wire.encode_request("m", "r1", [1, 2, 3], 7,
                                    temperature=0.5, top_p=0.9, rng=rng,
                                    emit_start=4, trace_id="t1")
        header, prompt = wire.decode_request(frame)
        assert header["model"] == "m" and header["request_id"] == "r1"
        assert header["n_tokens"] == 7 and header["emit_start"] == 4
        assert header["trace_id"] == "t1"
        np.testing.assert_array_equal(header["rng"], rng)
        np.testing.assert_array_equal(prompt, [1, 2, 3])

    def test_reply_roundtrip_and_error(self):
        frame = wire.encode_reply("r1", 2, [4, 5], done=True, model="m",
                                  version=3, error=ShedError("busy"))
        header, toks = wire.decode_reply(frame)
        assert header["seq"] == 2 and header["done"]
        np.testing.assert_array_equal(toks, [4, 5])
        err = wire.reply_error(header)
        assert isinstance(err, ShedError) and "busy" in str(err)

    @pytest.mark.parametrize("mutate", [
        lambda f: f[:6],                              # truncated
        lambda f: b"XXXX" + f[4:],                    # unknown magic
        lambda f: wire.REPLY_MAGIC + f[4:],           # wrong known magic
        lambda f: f[:4] + struct.pack("<I", 1 << 28) + f[8:],  # hlen lie
        lambda f: f[:8] + b"\xff" * 16 + f[24:],      # garbage JSON
        lambda f: f[:-5],                             # cut ndarray bytes
        lambda f: 12345,                              # not bytes at all
    ])
    def test_corruption_is_typed(self, mutate):
        frame = wire.encode_request("m", "r", [1, 2], 3)
        with pytest.raises(wire.WireFormatError):
            wire.decode_request(mutate(frame))

    def test_non_dict_header_typed(self):
        bad = wire.REQUEST_MAGIC + struct.pack("<I", 2) + b"[]"
        with pytest.raises(wire.WireFormatError, match="JSON object"):
            wire.decode_request(bad)

    def test_missing_fields_typed(self):
        frame = wire.encode_request("m", "r", [1], 1)
        payload = frame[8 + struct.unpack_from("<I", frame, 4)[0]:]
        bad = wire.REQUEST_MAGIC + struct.pack("<I", 2) + b"{}" + payload
        with pytest.raises(wire.WireFormatError, match="missing"):
            wire.decode_request(bad)

    def test_malformed_rng_typed(self):
        import json
        hdr = json.dumps({"model": "m", "request_id": "r",
                          "n_tokens": 1, "rng": ["x", "y"]}).encode()
        frame = wire.encode_request("m", "r", [1], 1)
        payload = frame[8 + struct.unpack_from("<I", frame, 4)[0]:]
        bad = wire.REQUEST_MAGIC + struct.pack("<I", len(hdr)) + hdr \
            + payload
        with pytest.raises(wire.WireFormatError, match="rng"):
            wire.decode_request(bad)

    def test_handoff_requires_kv_shape(self):
        header = {k: 0 for k in wire.HANDOFF_FIELDS}
        header["block_len"] = 4
        with pytest.raises(wire.WireFormatError, match="stacked K/V"):
            wire.decode_handoff(wire._frame(
                wire.HANDOFF_MAGIC, header, np.zeros((2, 3), np.float32)))

    def test_socket_framing_roundtrip_and_bound(self):
        a, b = socket.socketpair()
        try:
            frame = wire.encode_reply("r", 0, [1, 2, 3], done=False)
            wire.send_frame(a, frame)
            assert wire.recv_frame(b) == frame
            # corrupt length prefix past the wire bound: typed
            a.sendall(struct.pack("<I", wire.MAX_FRAME_BYTES + 1))
            with pytest.raises(wire.WireFormatError, match="bound"):
                wire.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_recv_frame_peer_close(self):
        a, b = socket.socketpair()
        a.close()
        try:
            with pytest.raises(ConnectionError):
                wire.recv_frame(b)
        finally:
            b.close()


# ================================================ disaggregated PFD
class TestDisaggregation:
    def test_split_pipeline_greedy_parity(self, net, prompts, ref):
        pre = PrefillWorker(net, n_slots=4, n_blocks=48, block_len=4)
        dec = DecodeWorker(net, n_slots=6, n_blocks=64, block_len=4)
        out = run_disaggregated(pre, dec, list(prompts), N_TOK)
        for got, want in zip(out, ref):
            np.testing.assert_array_equal(got, want)

    def test_split_pipeline_over_socket(self, net, prompts, ref):
        pre = PrefillWorker(net, n_slots=4, n_blocks=48, block_len=4)
        dec = DecodeWorker(net, n_slots=6, n_blocks=64, block_len=4)
        tx, rx = socket.socketpair()
        try:
            out = run_disaggregated(pre, dec, list(prompts[:3]), N_TOK,
                                    channel=(tx, rx))
        finally:
            tx.close()
            rx.close()
        for got, want in zip(out, ref[:3]):
            np.testing.assert_array_equal(got, want)

    def test_single_token_needs_no_handoff(self, net, prompts):
        pre = PrefillWorker(net, n_slots=2, n_blocks=16, block_len=4)
        first, frame = pre.prefill(prompts[0], 1)
        assert frame is None
        want = generate(net, prompts[:1], 1, temperature=0)[0]
        assert [first] == [int(t) for t in want]

    def test_adopt_rejects_block_len_mismatch(self, net, prompts):
        pre = PrefillWorker(net, n_slots=2, n_blocks=16, block_len=4)
        _, frame = pre.prefill(prompts[0], N_TOK)
        dec = DecodeWorker(net, n_slots=2, n_blocks=16, block_len=8)
        with pytest.raises(ValueError, match="block_len"):
            dec.adopt(frame)


# ================================================= serving directory
class TestServingDirectory:
    def test_serving_members_skip_training_ranks(self, coord, net):
        from deeplearning4j_tpu.parallel.elastic import ElasticClient
        trainer = ElasticClient(coord.address, "trainer-0",
                                heartbeat_interval_s=0.05)
        trainer.register(device_count=1)
        w = _worker(net, coord.address)
        try:
            deadline = time.monotonic() + 10
            status = {}
            while time.monotonic() < deadline:
                status = trainer.status()
                plan = status.get("plan") or {}
                if plan.get("serving_members") and plan.get("members"):
                    break
                time.sleep(0.05)
            plan = status["plan"]
            # the trainer keeps rank 0 of a world of ONE — serving
            # members never shift training ranks
            assert [m["token"] for m in plan["members"]] == ["trainer-0"]
            assert [m["token"] for m in plan["serving_members"]] \
                == [w.token]
            d = serving_directory(status, "m")
            assert len(d["replicas"]) == 1
            r = d["replicas"][0]
            assert r["port"] == w.port and r["version"] == 1
            assert set(r["load"]) >= {"queue_depth",
                                      "outstanding_tokens",
                                      "ewma_tok_s", "open_streams"}
        finally:
            w.stop()
            trainer.stop()

    def test_directory_filters_by_model(self, coord, net):
        w = _worker(net, coord.address)
        try:
            from deeplearning4j_tpu.parallel.elastic import retry_request
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                status = retry_request(coord.address,
                                       {"op": "status"})["status"]
                if serving_directory(status, "m")["replicas"]:
                    break
                time.sleep(0.05)
            assert serving_directory(status, "other")["replicas"] == []
        finally:
            w.stop()


# ==================================================== replica plane
class TestReplicaPlane:
    def test_round_trip_parity_and_version_tag(self, coord, net,
                                               prompts, ref):
        w = _worker(net, coord.address)
        client = ReplicaClient(w.host, w.port)
        try:
            streams = [client.submit("m", p, N_TOK) for p in prompts]
            for s, want in zip(streams, ref):
                np.testing.assert_array_equal(s.result(60), want)
                assert s.version == 1
                assert s.t_first is not None
        finally:
            client.close()
            w.stop()

    def test_mid_stream_death_is_typed(self, coord, net, prompts):
        w = _worker(net, coord.address)
        client = ReplicaClient(w.host, w.port)
        try:
            streams = [client.submit("m", p, 24) for p in prompts[:3]]
            time.sleep(0.1)
            w.stop()            # hard mid-stream death
            for s in streams:
                with pytest.raises(ReplicaLostError) as ei:
                    s.result(30)
                assert ei.value.request_id == s.request_id
                assert ei.value.last_seq >= -1
                assert ei.value.tokens == s.tokens
        finally:
            client.close()
            w.stop()

    def test_emit_start_continuation_parity(self, net, prompts):
        """The migration seam itself: a sampled stream cut at K tokens
        resumes on a DIFFERENT server as prompt+received with
        emit_start=K, bit-equal to the uninterrupted stream."""
        rng = np.asarray([11, 17], np.uint32)
        a = GenerationServer(net, n_slots=2, n_blocks=32, block_len=4)
        a.start()
        try:
            full = a.generate_async(prompts[0], 10, temperature=0.8,
                                    rng=rng).result(60)
        finally:
            a.stop()
        k = 4
        b = GenerationServer(net, n_slots=2, n_blocks=32, block_len=4)
        b.start()
        try:
            head = list(full[:k])
            cont = b.generate_async(
                np.concatenate([prompts[0], full[:k]]), 10 - k,
                temperature=0.8, rng=rng, emit_start=k).result(60)
        finally:
            b.stop()
        np.testing.assert_array_equal(head + list(cont), full)


# ============================================= router: balance + shed
class TestRouterReplicated:
    def test_least_loaded_balance_and_parity(self, coord, net, prompts,
                                             ref):
        w1 = _worker(net, coord.address)
        w2 = _worker(net, coord.address)
        rset = ReplicaSet(coord.address, "m", refresh_s=0.05)
        router = FleetRouter()
        router.attach_replicas("m", rset)
        try:
            _wait_replicas(rset, 2)
            streams = [router.submit("m", p, N_TOK) for p in prompts]
            for s, want in zip(streams, ref):
                assert isinstance(s, MigratingStream)
                np.testing.assert_array_equal(s.result(60), want)
            assert {s.replica for s in streams} \
                == {w1.token, w2.token}
        finally:
            rset.close()
            w1.stop()
            w2.stop()

    def test_sheds_only_when_every_replica_is_past_budget(
            self, coord, net, prompts):
        w1 = _worker(net, coord.address)
        w2 = _worker(net, coord.address)
        rset = ReplicaSet(coord.address, "m", refresh_s=0.05)
        router = FleetRouter(max_queue=0)   # every replica reads "full"
        router.attach_replicas("m", rset)
        try:
            _wait_replicas(rset, 2)
            with pytest.raises(ShedError, match="all 2 live replicas"):
                router.submit("m", prompts[0], N_TOK)
            # raising max_queue admits again — balance before shed
            router.max_queue = 64
            s = router.submit("m", prompts[0], N_TOK)
            s.result(60)
        finally:
            rset.close()
            w1.stop()
            w2.stop()

    def test_kill_drill_zero_dropped_streams(self, coord, net, prompts,
                                             ref):
        """Kill one of two replicas mid-flood: every accepted stream
        still finishes (migrated, greedy-bit-equal) and the set
        converges to the survivor."""
        w1 = _worker(net, coord.address)
        w2 = _worker(net, coord.address)
        rset = ReplicaSet(coord.address, "m", refresh_s=0.05)
        router = FleetRouter()
        router.attach_replicas("m", rset)
        try:
            _wait_replicas(rset, 2)
            flood = [router.submit("m", p, 24)
                     for p in list(prompts) * 2]
            time.sleep(0.1)
            w2.stop()           # mid-flood death
            want = generate(net, np.asarray(list(prompts) * 2), 24,
                            temperature=0)
            for s, w_ in zip(flood, want):
                np.testing.assert_array_equal(s.result(120), w_)
            assert any(s.migrations > 0 for s in flood)
            _wait_replicas(rset, 1)
            assert [t for t, _, _ in rset.backends()] == [w1.token]
            # post-kill traffic lands on the survivor
            s = router.submit("m", prompts[0], N_TOK)
            s.result(60)
            assert s.replica == w1.token
        finally:
            rset.close()
            w1.stop()
            w2.stop()

    def test_directory_eviction_migrates_without_deadlock(
            self, coord, net, prompts):
        """A replica evicted from the serving DIRECTORY while its
        socket still works and streams are in flight: refresh() closes
        the client, whose failing streams migrate SYNCHRONOUSLY on the
        refreshing thread and re-enter refresh()/backends() on the same
        set — a regression to closing under the set lock wedges that
        thread (and every future submit) forever."""
        w1 = _worker(net, coord.address)
        w2 = _worker(net, coord.address)
        rset = ReplicaSet(coord.address, "m", refresh_s=0.05)
        router = FleetRouter()
        router.attach_replicas("m", rset)
        try:
            _wait_replicas(rset, 2)
            flood = [router.submit("m", p, 24)
                     for p in list(prompts) * 2]
            time.sleep(0.1)
            # vanish from the directory WITHOUT breaking the socket —
            # heartbeats off first, or the beat loop re-registers
            w2._elastic.stop()
            w2._elastic.leave("eviction drill")
            converged = threading.Event()

            def _refresh_until_survivor():
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    rset.refresh(force=True)
                    if [t for t, _, _ in rset.backends()] \
                            == [w1.token]:
                        converged.set()
                        return
                    time.sleep(0.05)

            t = threading.Thread(target=_refresh_until_survivor,
                                 daemon=True)
            t.start()
            assert converged.wait(15), \
                "refresh() wedged evicting a replica with live streams"
            want = generate(net, np.asarray(list(prompts) * 2), 24,
                            temperature=0)
            for s, w_ in zip(flood, want):
                np.testing.assert_array_equal(s.result(120), w_)
            assert any(s.migrations > 0 for s in flood)
        finally:
            rset.close()
            w1.stop()
            w2.stop()

    def test_sampled_migration_keeps_fold_chain(self, coord, net,
                                                prompts):
        rng = np.asarray([7, 29], np.uint32)
        srv = GenerationServer(net, n_slots=2, n_blocks=32, block_len=4)
        srv.start()
        try:
            want = srv.generate_async(prompts[0], 24, temperature=0.8,
                                      rng=rng).result(60)
        finally:
            srv.stop()
        w1 = _worker(net, coord.address)
        w2 = _worker(net, coord.address)
        rset = ReplicaSet(coord.address, "m", refresh_s=0.05)
        router = FleetRouter()
        router.attach_replicas("m", rset)
        try:
            _wait_replicas(rset, 2)
            streams = [router.submit("m", prompts[0], 24,
                                     temperature=0.8, rng=rng)
                       for _ in range(4)]
            time.sleep(0.1)
            w2.stop()
            for s in streams:
                np.testing.assert_array_equal(s.result(120), want)
        finally:
            rset.close()
            w1.stop()
            w2.stop()


# ======================================================== migration
class TestQueuedMigration:
    def test_export_adopt_queued(self, net, prompts, ref):
        """Queued-but-unstarted requests move between servers
        wholesale: same stream object, the adopting server resolves
        it bit-equal."""
        a = GenerationServer(net, n_slots=2, n_blocks=32, block_len=4)
        b = GenerationServer(net, n_slots=4, n_blocks=48, block_len=4)
        a.start()
        # never give a's scheduler a chance: stall it behind a long
        # stream, then export the still-queued tail
        blocker = a.generate_async(prompts[0], 24)
        queued = [a.generate_async(p, N_TOK) for p in prompts[1:4]]
        moved = a.export_queued()
        # at least the tail moves; the blocker moves too if the
        # scheduler hadn't admitted it yet — both are legal
        assert 3 <= len(moved) <= 4
        b.start()
        try:
            assert b.adopt_queued(moved) == len(moved)
            for s, want in zip(queued, ref[1:4]):
                np.testing.assert_array_equal(s.result(60), want)
            blocker.result(60)
            a.drain(timeout=60)
            assert a.open_streams == 0 and b.open_streams == 0
        finally:
            a.stop()
            b.stop()

    def test_swap_migrates_queued_to_successor(self, tmp_path, net,
                                               prompts):
        from deeplearning4j_tpu.serving import FleetServer, ModelRegistry
        net2 = tiny_lm(seed=9)
        reg = ModelRegistry(tmp_path)
        reg.publish("m", net)
        reg.publish("m", net2)
        fleet = FleetServer(reg)
        # a shape no other test in this process compiles: the
        # incumbent's first admission wave stalls in jit compile for
        # seconds, pinning the tail in the queue while swap() exports
        # it — the migration is deterministic, not a race
        fleet.deploy("m", version=1, n_slots=2, n_blocks=36,
                     block_len=4)
        srv = fleet.server("m")
        inflight = [srv.generate_async(p, N_TOK) for p in prompts[:2]]
        deadline = time.monotonic() + 30
        while srv.queue_depth() and time.monotonic() < deadline:
            time.sleep(0.002)   # both admitted (compiling) on v1
        queued = [srv.generate_async(p, N_TOK) for p in prompts[2:5]]
        fleet.swap("m", version=2, drain_timeout=120)
        try:
            ref1 = generate(net, prompts[:2], N_TOK, temperature=0)
            ref2 = generate(net2, prompts[2:5], N_TOK, temperature=0)
            # in-flight on v1 finished on v1 (version parity) ...
            for s, want in zip(inflight, ref1):
                np.testing.assert_array_equal(s.result(60), want)
            # ... and the queued tail decoded ENTIRELY on the v2
            # successor
            for s, want in zip(queued, ref2):
                np.testing.assert_array_equal(s.result(60), want)
        finally:
            fleet.stop()


# ============================================== autoscaler: replicas
class _FakeReplica:
    def __init__(self):
        self.stopped = False

    def stop(self):
        self.stopped = True


class TestAutoscalerReplicas:
    def _fleet(self, tmp_path, net):
        from deeplearning4j_tpu.serving import FleetServer, ModelRegistry
        reg = ModelRegistry(tmp_path)
        reg.publish("m", net)
        fleet = FleetServer(reg)
        fleet.deploy("m", n_slots=2, n_blocks=16, block_len=4,
                     max_queue=64)
        return fleet

    def test_grow_replicas_at_vertical_cap(self, tmp_path, net,
                                           prompts):
        from deeplearning4j_tpu.serving import FleetAutoscaler
        fleet = self._fleet(tmp_path, net)
        mgr = ReplicaManager(lambda: _FakeReplica(), min_replicas=1,
                             max_replicas=3)
        mgr.grow()
        scaler = FleetAutoscaler(
            fleet, queue_depth_high=0, max_slots=2, max_blocks=16,
            replicas=mgr)
        try:
            srv = fleet.server("m")
            streams = [srv.generate_async(p, N_TOK) for p in prompts]
            made = scaler.check()
            for s in streams:
                s.result(60)
            grow = [r for r in made
                    if r.get("action") == "grow_replicas"]
            assert grow and mgr.count() == 2
            assert grow[0]["replicas"] == 2
            assert "queue_depth" in grow[0]["reason"]
        finally:
            mgr.stop()
            fleet.stop()

    def test_shrink_after_idle_passes(self, tmp_path, net):
        from deeplearning4j_tpu.serving import FleetAutoscaler
        fleet = self._fleet(tmp_path, net)
        fakes = []

        def factory():
            fakes.append(_FakeReplica())
            return fakes[-1]

        mgr = ReplicaManager(factory, min_replicas=1, max_replicas=3)
        mgr.grow()
        mgr.grow()
        scaler = FleetAutoscaler(fleet, replicas=mgr,
                                 replica_idle_passes=3)
        try:
            made = []
            for _ in range(3):
                made += scaler.check()
            shrink = [r for r in made
                      if r.get("action") == "shrink_replicas"]
            assert shrink and mgr.count() == 1
            assert shrink[0]["replicas"] == 1
            # newest-first: the SECOND fake was released, the first
            # (warmed) replica survives
            assert fakes[1].stopped and not fakes[0].stopped
        finally:
            mgr.stop()
            fleet.stop()

    def test_manager_bounds(self):
        mgr = ReplicaManager(lambda: _FakeReplica(), min_replicas=1,
                             max_replicas=2)
        assert mgr.grow() and mgr.grow() and not mgr.grow()
        assert mgr.count() == 2
        assert mgr.shrink() and not mgr.shrink()
        assert mgr.count() == 1
        with pytest.raises(ValueError):
            ReplicaManager(lambda: None, min_replicas=2, max_replicas=1)
