"""Transformer encoder stack: LayerNormalization, encoder block,
positional encoding, zoo TransformerClassifier / TransformerLM
(beyond-reference long-context models; SURVEY §5)."""

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers import (
    LayerNormalization,
    PositionalEncodingLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.zoo import TransformerClassifier, TransformerLM


class TestLayerNormalization:
    def test_normalizes_last_axis(self):
        ln = LayerNormalization(n_out=8)
        p = ln.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((4, 6, 8)) * 5 + 3, jnp.float32)
        y, _ = ln.forward(p, {}, x)
        np.testing.assert_allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y.std(-1)), 1.0, atol=1e-2)

    def test_gamma_beta_applied(self):
        ln = LayerNormalization(n_out=4)
        p = {"gamma": jnp.full((4,), 2.0), "beta": jnp.full((4,), 1.0)}
        x = jnp.asarray(np.random.default_rng(1)
                        .standard_normal((3, 4)), jnp.float32)
        y, _ = ln.forward(p, {}, x)
        np.testing.assert_allclose(np.asarray(y.mean(-1)), 1.0, atol=1e-5)


class TestPositionalEncoding:
    def test_signal_added_and_distinct_positions(self):
        pe = PositionalEncodingLayer(n_out=16)
        x = jnp.zeros((2, 10, 16))
        y, _ = pe.forward({}, {}, x)
        y = np.asarray(y)
        assert y.shape == (2, 10, 16)
        # all positions get distinct encodings
        assert len({tuple(np.round(y[0, t], 5)) for t in range(10)}) == 10
        np.testing.assert_allclose(y[0], y[1])   # batch-independent


class TestEncoderBlock:
    def test_shape_preserved_and_grads_flow(self):
        blk = TransformerEncoderBlock(n_in=32, n_heads=4)
        p = blk.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 7, 32)), jnp.float32)
        y, _ = blk.forward(p, {}, x)
        assert y.shape == x.shape

        def loss(pp):
            out, _ = blk.forward(pp, {}, x)
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(p)
        for k, v in g.items():
            assert np.isfinite(np.asarray(v)).all(), k
        assert float(jnp.abs(g["attn_Wq"]).sum()) > 0
        assert float(jnp.abs(g["ff_W1"]).sum()) > 0

    def test_causal_blocks_no_future_leak(self):
        blk = TransformerEncoderBlock(n_in=16, n_heads=2, causal=True,
                                      use_flash=False)
        p = blk.init_params(jax.random.PRNGKey(1))
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((1, 6, 16)), jnp.float32)
        y1, _ = blk.forward(p, {}, x)
        x2 = x.at[0, 4].set(99.0)     # perturb a LATER position
        y2, _ = blk.forward(p, {}, x2)
        np.testing.assert_allclose(np.asarray(y1[0, :4]),
                                   np.asarray(y2[0, :4]), rtol=1e-5)


class TestTransformerZoo:
    def test_classifier_learns_token_presence(self):
        # class 1 iff token 0 appears in the sequence
        rng = np.random.default_rng(0)
        n, T = 256, 12
        ids = rng.integers(1, 30, (n, T))
        has = rng.random(n) < 0.5
        for i in np.nonzero(has)[0]:
            ids[i, rng.integers(0, T)] = 0
        y = np.eye(2, dtype=np.float32)[has.astype(int)]
        from deeplearning4j_tpu.nn.layers.pooling import PoolingType
        net = TransformerClassifier(vocab_size=30, num_classes=2,
                                    d_model=32, n_layers=1, n_heads=4,
                                    pooling=PoolingType.MAX, seed=7).init()
        net.fit(ids.astype(np.float32), y, epochs=20, batch_size=64)
        pred = np.asarray(net.output(ids.astype(np.float32))).argmax(1)
        acc = (pred == has.astype(int)).mean()
        assert acc > 0.9, acc

    def test_lm_learns_deterministic_sequence(self):
        # cyclic sequence: next token = (t + 1) % V — causal LM must nail it
        V, T, B = 11, 16, 32
        starts = np.arange(B) % V
        ids = (starts[:, None] + np.arange(T)[None, :]) % V
        x = ids.astype(np.float32)
        y = np.eye(V, dtype=np.float32)[(ids + 1) % V]
        lm = TransformerLM(vocab_size=V, d_model=32, n_layers=1,
                           n_heads=4, seed=3).init()
        lm.fit(x, y, epochs=60, batch_size=B, shuffle=False)
        out = np.asarray(lm.output(x))
        pred = out.argmax(-1)
        acc = (pred == (ids + 1) % V).mean()
        assert acc > 0.95, acc

    def test_serde_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer
        net = TransformerClassifier(vocab_size=20, num_classes=3,
                                    d_model=16, n_layers=1,
                                    n_heads=2).init()
        ids = np.random.default_rng(0).integers(0, 20, (4, 8)).astype(np.float32)
        want = np.asarray(net.output(ids))
        path = str(tmp_path / "tf.zip")
        ModelSerializer.write_model(net, path)
        clone = ModelSerializer.restore_model(path)
        got = np.asarray(clone.output(ids))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_embedding_vocab_inferred_from_recurrent_input():
    # regression: n_in must come from the recurrent type's feature size
    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingLayer, GlobalPoolingLayer, OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(EmbeddingLayer(n_out=8))
            .layer(GlobalPoolingLayer())
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(100))
            .build())
    assert conf.layers[0].n_in == 100
    net = MultiLayerNetwork(conf).init()
    assert net.params["0"]["W"].shape == (100, 8)
    ids = np.random.default_rng(0).integers(0, 100, (3, 5)).astype(np.float32)
    assert np.asarray(net.output(ids)).shape == (3, 2)


class TestRematParity:
    """`remat=True` recomputes block activations in backward — loss,
    gradients, and the training trajectory must be identical to the
    stored-activation path (jax.checkpoint changes memory, not math)."""

    def test_lm_training_trajectory_identical(self):
        import numpy as np
        from deeplearning4j_tpu.zoo.transformer import TransformerLM

        V, B, T = 20, 4, 12
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (B, T))
        x = ids.astype(np.float32)
        y = np.eye(V, dtype=np.float32)[(ids + 1) % V]

        losses = {}
        for remat in (False, True):
            lm = TransformerLM(vocab_size=V, d_model=16, n_layers=2,
                               n_heads=4, max_len=T, remat=remat)
            net = lm.init()
            net.fit(x, y, epochs=3, batch_size=B, shuffle=False)
            losses[remat] = net.score_value
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-5, atol=1e-6)

    def test_parity_holds_with_dropout(self):
        """rng rides through jax.checkpoint as an explicit argument, so
        the backward-pass recompute draws the SAME dropout masks — with
        dropout enabled, remat on/off must still match exactly."""
        import numpy as np
        from deeplearning4j_tpu.zoo.transformer import TransformerClassifier

        V, B, T = 16, 8, 10
        rng = np.random.default_rng(1)
        ids = rng.integers(0, V, (B, T)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, B)]

        losses = {}
        for remat in (False, True):
            clf = TransformerClassifier(vocab_size=V, num_classes=3,
                                        d_model=16, n_layers=2, n_heads=4,
                                        max_len=T, dropout=0.8, remat=remat)
            net = clf.init()
            net.fit(ids, y, epochs=3, batch_size=B, shuffle=False)
            losses[remat] = net.score_value
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-5, atol=1e-6)

    def test_remat_survives_config_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.zoo.transformer import TransformerLM

        conf = TransformerLM(vocab_size=10, d_model=8, n_layers=1,
                             n_heads=2, max_len=8, remat=True).conf()
        js = conf.to_json()
        clone = MultiLayerConfiguration.from_json(js)
        blocks = [l for l in clone.layers
                  if getattr(l, "layer_name", "") == "transformer_encoder"]
        assert blocks and all(b.remat for b in blocks)


class TestTransformerTransferLearning:
    """Fine-tune a 'pretrained' TransformerClassifier on a new label
    set: freeze the encoder stack, replace the head — the reference
    transfer-learning workflow applied to the beyond-reference model
    family."""

    def test_freeze_encoder_swap_head(self):
        from deeplearning4j_tpu.transferlearning import TransferLearning

        V, B, T = 20, 16, 10
        rng = np.random.default_rng(0)
        ids = rng.integers(1, V, (B, T)).astype(np.float32)
        y2 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, B)]

        base = TransformerClassifier(vocab_size=V, num_classes=4,
                                     d_model=16, n_layers=1, n_heads=4,
                                     max_len=T).init()
        base.fit(ids, np.eye(4, dtype=np.float32)[rng.integers(0, 4, B)],
                 epochs=1, batch_size=B)

        # freeze through the pooling layer (index of last non-output
        # layer), re-head for 2 classes
        n_layers = len(base.layers)
        tuned = (TransferLearning.Builder(base)
                 .set_feature_extractor(n_layers - 2)
                 .n_out_replace(n_layers - 1, 2)
                 .build())
        before = {k: np.asarray(v).copy()
                  for k, v in tuned.param_table().items()}
        head = str(n_layers - 1)
        tuned.fit(ids, y2, epochs=2, batch_size=B)
        out = np.asarray(tuned.output(ids))
        assert out.shape == (B, 2)
        # frozen encoder params unchanged; the head must actually move
        head_moved = False
        for k, v in tuned.param_table().items():
            if k.startswith(head):
                head_moved = head_moved or not np.allclose(
                    np.asarray(v), before[k], atol=1e-7)
            else:
                np.testing.assert_allclose(np.asarray(v), before[k],
                                           atol=1e-7, err_msg=k)
        assert head_moved, "output layer params did not train"


class TestKVCacheDecoding:
    """Streaming decode with fixed-size KV caches (the transformer
    analogue of rnnTimeStep): stepwise cached outputs must equal the
    full causal forward at every position."""

    def _net(self, V=17, T=12):
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        return TransformerLM(vocab_size=V, d_model=16, n_layers=2,
                             n_heads=4, max_len=T, seed=3).init(), V, T

    def test_stepwise_matches_full_forward(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.recurrent import (
            BaseRecurrentLayer)
        net, V, T = self._net()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (2, T)).astype(np.float32)
        full = np.asarray(net.output(ids))            # [B, T, V]

        carries = {str(i): layer.init_carry(2, jnp.float32)
                   for i, layer in enumerate(net.layers)
                   if isinstance(layer, BaseRecurrentLayer)}
        for t in range(T):
            h, _, carries, _, _ = net._forward_core(
                net.params, net.net_state, ids[:, t:t + 1],
                train=False, rng=None, carries=carries)
            np.testing.assert_allclose(np.asarray(h[:, 0]), full[:, t],
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"position {t}")

    def test_prompt_then_steps_matches(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.recurrent import (
            BaseRecurrentLayer)
        net, V, T = self._net()
        rng = np.random.default_rng(1)
        ids = rng.integers(0, V, (2, T)).astype(np.float32)
        full = np.asarray(net.output(ids))
        carries = {str(i): layer.init_carry(2, jnp.float32)
                   for i, layer in enumerate(net.layers)
                   if isinstance(layer, BaseRecurrentLayer)}
        # multi-token prompt in one call, then single-token steps
        P = 5
        h, _, carries, _, _ = net._forward_core(
            net.params, net.net_state, ids[:, :P], train=False,
            rng=None, carries=carries)
        np.testing.assert_allclose(np.asarray(h), full[:, :P],
                                   rtol=2e-4, atol=2e-5)
        for t in range(P, T):
            h, _, carries, _, _ = net._forward_core(
                net.params, net.net_state, ids[:, t:t + 1],
                train=False, rng=None, carries=carries)
            np.testing.assert_allclose(np.asarray(h[:, 0]), full[:, t],
                                       rtol=2e-4, atol=2e-5)

    def test_generate_shapes_and_greedy_determinism(self):
        from deeplearning4j_tpu.zoo.transformer import generate
        net, V, T = self._net()
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, V, (3, 4))
        out1 = generate(net, prompt, 6, temperature=0)
        out2 = generate(net, prompt, 6, temperature=0)
        assert out1.shape == (3, 6)
        assert (out1 == out2).all()
        assert ((0 <= out1) & (out1 < V)).all()
        # greedy continuation must equal argmax of the full forward fed
        # with the sampled prefix (teacher-forcing cross-check)
        seq = np.concatenate([prompt.astype(np.float32),
                              out1.astype(np.float32)], axis=1)
        full = np.asarray(net.output(seq))
        want = full[:, prompt.shape[1] - 1:-1].argmax(-1)
        np.testing.assert_array_equal(out1, want)

    def test_generate_rejects_cache_overflow(self):
        from deeplearning4j_tpu.zoo.transformer import generate
        net, V, T = self._net(T=8)
        prompt = np.zeros((1, 4), np.int32)
        with pytest.raises(ValueError, match="cache length"):
            generate(net, prompt, 10, temperature=0)


class TestTransformerStreamingDepth:
    def test_graph_container_kv_cache_stream(self):
        # transformer blocks stream inside ComputationGraph too (same
        # BaseRecurrentLayer carry plumbing as MultiLayerNetwork)
        import jax.numpy as jnp
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.common.weights import WeightInit
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingLayer, PositionalEncodingLayer, RnnOutputLayer,
            TransformerEncoderBlock)
        from deeplearning4j_tpu.nn.layers.recurrent import (
            BaseRecurrentLayer)

        from deeplearning4j_tpu.nn.graph import (
            ComputationGraphConfiguration)

        V, T = 13, 10
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER))
        g.add_inputs("ids")
        g.add_layer("emb", EmbeddingLayer(n_in=V, n_out=16), "ids")
        g.add_layer("pos", PositionalEncodingLayer(max_len=T), "emb")
        g.add_layer("blk", TransformerEncoderBlock(
            n_heads=4, causal=True, cache_len=T), "pos")
        g.add_layer("out", RnnOutputLayer(
            n_out=V, activation="softmax", loss="mcxent"), "blk")
        g.set_outputs("out")
        g.set_input_types(InputType.recurrent(V))
        net = ComputationGraph(g.build()).init(5)
        rng = np.random.default_rng(3)
        ids = rng.integers(0, V, (2, T)).astype(np.float32)
        full = np.asarray(net.output(ids))

        carries = {n: layer.init_carry(2, jnp.float32)
                   for n, layer in net._recurrent_nodes()}
        for t in range(T):
            acts, _, _, _ = net._forward_all(
                net.params, net.net_state, [ids[:, t:t + 1]],
                train=False, rng=None, carries=carries)
            h = acts[net.conf.network_outputs[0]]
            np.testing.assert_allclose(np.asarray(h[:, 0]), full[:, t],
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"position {t}")

    def test_tbptt_transformer_xl_training(self):
        # TBPTT chunks thread the KV cache (Transformer-XL recurrence):
        # training runs, loss decreases, positions continue across
        # chunk boundaries (would diverge if the cache reset)
        from deeplearning4j_tpu.nn.conf.builder import BackpropType
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        lm = TransformerLM(vocab_size=11, d_model=16, n_layers=1,
                           n_heads=4, max_len=16, seed=9)
        conf = lm.conf()
        conf.backprop_type = BackpropType.TRUNCATED_BPTT
        conf.tbptt_fwd_length = 4
        conf.tbptt_back_length = 4
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init(9)
        rng = np.random.default_rng(4)
        ids = rng.integers(0, 11, (4, 16))
        x = ids.astype(np.float32)
        y = np.eye(11, dtype=np.float32)[(ids + 1) % 11]
        scores = []
        for _ in range(6):
            net.fit(x, y, epochs=1, batch_size=4)
            scores.append(net.score_value)
        assert all(np.isfinite(s) for s in scores)
        assert scores[-1] < scores[0]

    def test_streaming_rejects_padding_mask(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import TransformerEncoderBlock
        blk = TransformerEncoderBlock(n_in=8, n_heads=2, causal=True,
                                      cache_len=8)
        params = blk.init_params(jax.random.PRNGKey(0))
        x = jnp.zeros((1, 2, 8))
        with pytest.raises(ValueError, match="padding mask"):
            blk.forward_with_carry(params, {}, x, blk.init_carry(1),
                                   mask=jnp.ones((1, 2)))

    def test_rnn_time_step_streams_token_ids(self):
        # the reference rnnTimeStep API works for transformers too:
        # rank-2 [B, T] is token ids for embedding-input nets (incl.
        # [B, 1] single-step decode), not a [B, F] feature row
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        net = TransformerLM(vocab_size=13, d_model=16, n_layers=1,
                            n_heads=4, max_len=10, seed=11).init()
        rng = np.random.default_rng(6)
        ids = rng.integers(0, 13, (2, 10)).astype(np.float32)
        full = np.asarray(net.output(ids))
        net.rnn_clear_previous_state()
        h = np.asarray(net.rnn_time_step(ids[:, :4]))     # prompt
        np.testing.assert_allclose(h, full[:, :4], rtol=2e-4, atol=2e-5)
        for t in range(4, 10):
            h = np.asarray(net.rnn_time_step(ids[:, t:t + 1]))
            np.testing.assert_allclose(h[:, 0], full[:, t],
                                       rtol=2e-4, atol=2e-5)

    def test_rnn_time_step_enforces_stream_budget(self):
        # streaming past cache_len used to silently clamp the last KV
        # slot (dynamic_update_slice) and corrupt later outputs; now
        # the host-side position tracker raises at the entry point
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        net = TransformerLM(vocab_size=13, d_model=16, n_layers=1,
                            n_heads=4, max_len=6, seed=11).init()
        ids = np.zeros((1, 4), np.float32)
        net.rnn_clear_previous_state()
        net.rnn_time_step(ids)                     # pos → 4
        net.rnn_time_step(ids[:, :2])              # pos → 6 (== budget)
        with pytest.raises(ValueError, match="stream budget"):
            net.rnn_time_step(ids[:, :1])
        # a new sequence resets the tracker
        net.rnn_clear_previous_state()
        net.rnn_time_step(ids)
        # an over-budget single call also raises
        net.rnn_clear_previous_state()
        with pytest.raises(ValueError, match="stream budget"):
            net.rnn_time_step(np.zeros((1, 7), np.float32))

    def test_tbptt_rejects_sequences_beyond_cache(self):
        from deeplearning4j_tpu.nn.conf.builder import BackpropType
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingLayer, RnnOutputLayer, TransformerEncoderBlock)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import DenseLayer
        V, T = 7, 12
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=V, n_out=8))
                .layer(TransformerEncoderBlock(n_heads=2, causal=True,
                                               cache_len=8))
                .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(V))
                .backprop_type(BackpropType.TRUNCATED_BPTT, 4)
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.zeros((2, T, V), np.float32)    # rank-3 → TBPTT chunking
        y = np.zeros((2, T, V), np.float32)
        y[..., 0] = 1.0
        with pytest.raises(ValueError, match="carry budget"):
            net.fit(x, y, epochs=1, batch_size=2)

    def test_graph_mixed_id_and_feature_inputs_squeeze_per_input(self):
        # advisor scenario: a graph mixing a token-id input with a
        # rank-2 [B, F] feature input must squeeze only the feature one
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingLayer, LSTM, RnnOutputLayer)
        V, D = 11, 6
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3)))
        g.add_inputs("ids", "feat")
        g.add_layer("emb", EmbeddingLayer(n_in=V, n_out=D), "ids")
        # the feature input feeds a recurrent consumer directly: at
        # rnn_time_step a rank-2 [B, F] here is ONE timestep and must
        # be expanded to [B, 1, F] even though an id input coexists
        # (the old global flag disabled the squeeze for all inputs)
        g.add_layer("rnn2", LSTM(n_in=4, n_out=D), "feat")
        g.add_vertex("cat", MergeVertex(), "emb", "rnn2")
        g.add_layer("rnn", LSTM(n_in=2 * D, n_out=D), "cat")
        g.add_layer("out", RnnOutputLayer(n_out=V, activation="softmax",
                                          loss="mcxent"), "rnn")
        g.set_outputs("out")
        g.set_input_types(InputType.recurrent(V),
                          InputType.recurrent(4))
        net = ComputationGraph(g.build()).init(3)
        # full-sequence reference
        T = 5
        rng = np.random.default_rng(4)
        ids_seq = rng.integers(0, V, (2, T)).astype(np.float32)
        feat_seq = rng.standard_normal((2, T, 4)).astype(np.float32)
        full = np.asarray(net.output(ids_seq, feat_seq))
        # stream one step at a time: ids as [B,1], features as [B,F]
        net.rnn_clear_previous_state()
        for t in range(T):
            out = np.asarray(net.rnn_time_step(
                ids_seq[:, t:t + 1], feat_seq[:, t]))
            assert out.shape == (2, V)
            np.testing.assert_allclose(out, full[:, t], rtol=2e-4,
                                       atol=2e-5)

    def test_generate_topk_topp_filters(self):
        from deeplearning4j_tpu.zoo.transformer import generate
        import jax
        net_lm = __import__("deeplearning4j_tpu.zoo.transformer",
                            fromlist=["TransformerLM"]).TransformerLM(
            vocab_size=17, d_model=16, n_layers=1, n_heads=4,
            max_len=24, seed=13).init()
        prompt = np.zeros((2, 2), np.int32)
        k0 = jax.random.PRNGKey(7)
        # top_k=1 is greedy regardless of temperature
        a = generate(net_lm, prompt, 6, temperature=1.0, top_k=1, rng=k0)
        g = generate(net_lm, prompt, 6, temperature=0)
        np.testing.assert_array_equal(a, g)
        # no-op filters reproduce unfiltered sampling bit-for-bit
        b = generate(net_lm, prompt, 6, temperature=1.0, rng=k0)
        c = generate(net_lm, prompt, 6, temperature=1.0, top_k=17,
                     rng=k0)
        d = generate(net_lm, prompt, 6, temperature=1.0, top_p=1.0,
                     rng=k0)
        np.testing.assert_array_equal(b, c)
        np.testing.assert_array_equal(b, d)

    def test_generate_rejects_bad_sampling_args(self):
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, generate)
        net = TransformerLM(vocab_size=17, d_model=16, n_layers=1,
                            n_heads=4, max_len=24, seed=13).init()
        prompt = np.zeros((1, 2), np.int32)
        with pytest.raises(ValueError, match="top_p"):
            generate(net, prompt, 4, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            generate(net, prompt, 4, top_k=0)
        with pytest.raises(ValueError, match="top_k"):
            generate(net, prompt, 4, top_k=99)

    def test_graph_rnn_time_step_token_ids(self):
        # the graph container's rnnTimeStep API streams token-id models
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.common.weights import WeightInit
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingLayer, PositionalEncodingLayer, RnnOutputLayer,
            TransformerEncoderBlock)
        V, T = 13, 8
        g = ComputationGraphConfiguration.graph_builder(
            NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER))
        g.add_inputs("ids")
        g.add_layer("emb", EmbeddingLayer(n_in=V, n_out=16), "ids")
        g.add_layer("pos", PositionalEncodingLayer(max_len=T), "emb")
        g.add_layer("blk", TransformerEncoderBlock(
            n_heads=4, causal=True, cache_len=T), "pos")
        g.add_layer("out", RnnOutputLayer(
            n_out=V, activation="softmax", loss="mcxent"), "blk")
        g.set_outputs("out")
        g.set_input_types(InputType.recurrent(V))
        net = ComputationGraph(g.build()).init(5)
        rng = np.random.default_rng(8)
        ids = rng.integers(0, V, (2, T)).astype(np.float32)
        full = np.asarray(net.output(ids))
        net.rnn_clear_previous_state()
        h = np.asarray(net.rnn_time_step(ids[:, :3]))
        np.testing.assert_allclose(h, full[:, :3], rtol=2e-4, atol=2e-5)
        for t in range(3, T):
            h = np.asarray(net.rnn_time_step(ids[:, t:t + 1]))
            np.testing.assert_allclose(h[:, 0], full[:, t],
                                       rtol=2e-4, atol=2e-5)

    def test_beam_search(self):
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, beam_search, generate)
        net = TransformerLM(vocab_size=17, d_model=16, n_layers=1,
                            n_heads=4, max_len=24, seed=13).init()
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, 17, (2, 3))
        ids, scores = beam_search(net, prompt, 6, beam_width=4)
        assert ids.shape == (2, 4, 6) and scores.shape == (2, 4)
        # beams sorted best-first
        assert (np.diff(scores, axis=1) <= 1e-6).all()
        # beam_width=1 equals greedy decoding
        g = generate(net, prompt, 6, temperature=0)
        b1, _ = beam_search(net, prompt, 6, beam_width=1)
        np.testing.assert_array_equal(b1[:, 0], g)
        # the reported beam scores must equal the true teacher-forced
        # accumulated logprob of the returned sequences (beam search is
        # NOT guaranteed to beat greedy for W>1, so assert bookkeeping
        # correctness, not monotonicity)
        def seq_logp_rows(seq):
            full = np.concatenate([prompt.astype(np.float32),
                                   seq.astype(np.float32)], 1)
            probs = np.asarray(net.output(full))
            out = np.zeros(seq.shape[0])
            for b in range(seq.shape[0]):
                for t in range(seq.shape[1]):
                    out[b] += np.log(max(
                        probs[b, prompt.shape[1] - 1 + t, seq[b, t]],
                        1e-9))
            return out
        np.testing.assert_allclose(seq_logp_rows(ids[:, 0]),
                                   scores[:, 0], rtol=1e-4, atol=1e-3)

    def test_beam_search_eos_freezes_finished(self):
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, beam_search)
        net = TransformerLM(vocab_size=11, d_model=16, n_layers=1,
                            n_heads=4, max_len=24, seed=3).init()
        prompt = np.zeros((1, 2), np.int32)
        ids, scores = beam_search(net, prompt, 8, beam_width=3, eos_id=5)
        # once a beam emits eos, every later token is eos
        for w in range(3):
            seq = ids[0, w]
            hits = np.nonzero(seq == 5)[0]
            if hits.size:
                assert (seq[hits[0]:] == 5).all()

    def test_beam_search_length_penalty(self):
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, beam_search)
        net = TransformerLM(vocab_size=11, d_model=16, n_layers=1,
                            n_heads=4, max_len=24, seed=3).init()
        prompt = np.zeros((1, 2), np.int32)
        # alpha=0 is the unnormalized ordering (argsort of raw scores)
        ids0, s0 = beam_search(net, prompt, 8, beam_width=3, eos_id=5,
                               length_penalty=0.0)
        assert (np.diff(s0, axis=1) <= 1e-6).all()
        # with alpha the beam SET is unchanged (pure rerank), and the
        # ORDER must equal the recomputed normalized-score ordering —
        # this fails if the norm is inverted, multiplied, or lengths
        # are computed wrong
        alpha = 1.0
        ids1, s1 = beam_search(net, prompt, 8, beam_width=3, eos_id=5,
                               length_penalty=alpha)
        assert sorted(map(tuple, ids0[0])) == sorted(map(tuple, ids1[0]))

        def norm_score(seq, raw):
            hit = np.nonzero(seq == 5)[0]
            L = hit[0] + 1 if hit.size else seq.size
            return raw / (((5.0 + L) / 6.0) ** alpha)

        ns = [norm_score(ids1[0, w], s1[0, w]) for w in range(3)]
        assert (np.diff(ns) <= 1e-6).all(), ns
        # and when beams have different lengths, alpha must actually be
        # able to change the winner relative to raw ordering whenever
        # the normalized ordering differs
        ns0 = [norm_score(ids0[0, w], s0[0, w]) for w in range(3)]
        if np.argmax(ns0) != 0:
            assert tuple(ids1[0, 0]) != tuple(ids0[0, 0])


class TestIntegerIdCarry:
    """generate()/beam_search() keep token ids INTEGER while carried
    standalone: a float32 round-trip silently collapses ids at the
    2^24 precision edge (16777217.0 == 16777216.0) — only the
    embedding gather consumes them, and it indexes with int32 either
    way."""

    def test_generate_feeds_integer_ids_to_embedding(self, monkeypatch):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.feedforward import (
            EmbeddingLayer)
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, generate)

        seen = []
        orig = EmbeddingLayer.forward

        def spy(self, params, state, x, **kw):
            seen.append(jnp.asarray(x).dtype)
            return orig(self, params, state, x, **kw)

        monkeypatch.setattr(EmbeddingLayer, "forward", spy)
        # fresh net -> fresh jit cache -> the prefill/decode traces run
        # through the spy exactly once each
        net = TransformerLM(vocab_size=17, d_model=16, n_layers=1,
                            n_heads=4, max_len=12, seed=9).init()
        out = generate(net, np.zeros((1, 3), np.int64), 4, temperature=0)
        assert out.shape == (1, 4)
        assert seen, "embedding never traced"
        assert all(np.issubdtype(d, np.integer) for d in seen), (
            f"token ids reached the embedding as {seen} — the float "
            "carry corrupts ids at the 2^24 edge")

    def test_embedding_gather_exact_at_float_precision_edge(self):
        """Ids straddling 2^24, gathered through a huge-vocab embedding
        table: the int path must hit exact rows where a float32 carry
        provably collapses neighbors."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.feedforward import (
            EmbeddingLayer)

        edge = 2 ** 24
        V = edge + 8
        layer = EmbeddingLayer(n_in=V, n_out=1, has_bias=False)
        layer.time_series_input = True
        # rows distinguishable mod 7 without allocating V*D rands
        W = (jnp.arange(V, dtype=jnp.int32) % 7).astype(
            jnp.float32)[:, None]
        ids = np.asarray([[edge - 1, edge, edge + 1, edge + 3]],
                         np.int64)
        out, _ = layer.forward({"W": W}, {}, jnp.asarray(ids))
        want = (ids % 7).astype(np.float32)[..., None]
        np.testing.assert_array_equal(np.asarray(out), want)
        # the float32 carry this guards against IS lossy here
        as_f32 = ids.astype(np.float32).astype(np.int64)
        assert (as_f32 != ids).any()

    def test_generate_beam_unchanged_by_int_carry(self):
        """Trajectory regression: greedy generate and beam_search stay
        deterministic and in-vocab after the int-id change (numerics
        must be untouched — the gather rows are identical)."""
        from deeplearning4j_tpu.zoo.transformer import (
            TransformerLM, beam_search, generate)

        net = TransformerLM(vocab_size=17, d_model=16, n_layers=2,
                            n_heads=4, max_len=12, seed=4).init()
        prompt = np.asarray([[3, 5, 1], [2, 2, 4]])
        g1 = generate(net, prompt, 5, temperature=0)
        g2 = generate(net, prompt.astype(np.float32), 5, temperature=0)
        np.testing.assert_array_equal(g1, g2)   # float prompts still ok
        seqs, scores = beam_search(net, prompt, 5, beam_width=2)
        assert seqs.shape == (2, 2, 5)
        np.testing.assert_array_equal(seqs[:, 0], g1)  # top beam = greedy
