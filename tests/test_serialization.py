"""ModelSerializer round-trip tests (reference: ModelSerializer zip of
configuration.json + coefficients + updaterState)."""

import numpy as np

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization,
    DenseLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.serializer import ModelSerializer


def test_multilayer_roundtrip(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(0.02)).list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    x, y = load_iris()
    net.fit(x, y, epochs=2, batch_size=50)
    path = tmp_path / "model.zip"
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_model(path)
    # params, state, updater state, counters and outputs all survive
    for k, v in net.param_table().items():
        np.testing.assert_allclose(np.asarray(v), np.asarray(net2.param_table()[k]))
    np.testing.assert_allclose(np.asarray(net.net_state["1"]["mean"]),
                               np.asarray(net2.net_state["1"]["mean"]))
    assert net2.iteration_count == net.iteration_count
    out1 = np.asarray(net.output(x[:8]))
    out2 = np.asarray(net2.output(x[:8]))
    np.testing.assert_allclose(out1, out2, atol=1e-6)
    # training continues seamlessly (updater state restored)
    m0 = np.asarray(net.updater_state["0"]["W"]["m"])
    m2 = np.asarray(net2.updater_state["0"]["W"]["m"])
    np.testing.assert_allclose(m0, m2)
    net2.fit(x, y, epochs=1, batch_size=50)


def test_lstm_roundtrip(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(LSTM(n_in=3, n_out=5))
            .layer(RnnOutputLayer(n_in=5, n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    path = tmp_path / "lstm.zip"
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_model(path)
    x = np.random.randn(2, 4, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), np.asarray(net2.output(x)),
                               atol=1e-6)


def test_graph_roundtrip(tmp_path):
    g = ComputationGraphConfiguration.graph_builder(
        NeuralNetConfiguration.builder().seed(9).updater(Adam(0.01)))
    g.add_inputs("in")
    g.add_layer("fc_1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
    g.add_vertex("res", ElementWiseVertex(op="add"), "fc_1", "fc_1")
    g.add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                   loss="mcxent"), "res")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    x, y = load_iris()
    net.fit(x, y, epochs=1, batch_size=50)
    path = tmp_path / "graph.zip"
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_model(path)
    assert isinstance(net2, ComputationGraph)
    np.testing.assert_allclose(np.asarray(net.output(x[:5])),
                               np.asarray(net2.output(x[:5])), atol=1e-6)


class TestConfigFormatVersion:
    """format_version stamping (reference role: the legacy-migration
    deserializers `MultiLayerConfigurationDeserializer.java:36` — a
    version field is what makes future migrations possible)."""

    def _conf(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        return (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())

    def test_round_trip_carries_version(self):
        import json
        from deeplearning4j_tpu.nn.conf.builder import (
            CONFIG_FORMAT_VERSION, MultiLayerConfiguration,
        )
        s = self._conf().to_json()
        assert json.loads(s)["format_version"] == CONFIG_FORMAT_VERSION
        conf2 = MultiLayerConfiguration.from_json(s)
        assert conf2.to_dict()["format_version"] == CONFIG_FORMAT_VERSION

    def test_future_version_rejected(self):
        import json
        import pytest
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        d = json.loads(self._conf().to_json())
        d["format_version"] = 999
        with pytest.raises(ValueError, match="newer than this build"):
            MultiLayerConfiguration.from_dict(d)

    def test_missing_version_treated_as_v1(self):
        import json
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        d = json.loads(self._conf().to_json())
        del d["format_version"]
        conf = MultiLayerConfiguration.from_dict(d)  # pre-versioning payload
        assert len(conf.layers) == 2

    def test_graph_config_versioned(self):
        import json
        import pytest
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

        b = NeuralNetConfiguration.builder().updater(Adam(1e-3))
        g = ComputationGraphConfiguration.graph_builder(b)
        g.add_inputs("in")
        g.add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "d")
        g.set_input_types(InputType.feed_forward(4))
        g.set_outputs("out")
        conf = g.build()
        d = json.loads(conf.to_json())
        assert d["format_version"] >= 1
        d["format_version"] = 999
        with pytest.raises(ValueError, match="newer than this build"):
            ComputationGraphConfiguration.from_dict(d)
