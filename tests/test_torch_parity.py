"""Cross-framework forward parity against torch (CPU) — an oracle
INDEPENDENT of this repo's numpy fixtures and of JAX itself. The
float64 gradient checker validates backward math against our own
forward; these tests validate the forward semantics themselves (padding
arithmetic, gate orderings, normalization epsilon placement, pooling
tie-breaking) against a second major framework.

Reference parallel: the cuDNN parity suites (`ValidateCudnnLSTM.java`,
`CuDNNGradientChecks.java`) validated one implementation against an
independent one the same way.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deeplearning4j_tpu.nn.layers import (  # noqa: E402
    LSTM,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode  # noqa: E402


def _init(layer, n_in, extra=None):
    import jax

    layer.n_in = n_in
    for k, v in (extra or {}).items():
        setattr(layer, k, v)
    params = layer.init_params(jax.random.PRNGKey(0), np.float32)
    state = (layer.init_state(np.float32)
             if hasattr(layer, "init_state") else {})
    return params, state


class TestConvParity:
    @pytest.mark.parametrize("mode,stride", [
        (ConvolutionMode.SAME, 1), (ConvolutionMode.SAME, 2),
        (ConvolutionMode.TRUNCATE, 1), (ConvolutionMode.TRUNCATE, 2),
    ])
    def test_conv2d_matches_torch(self, mode, stride):
        rng = np.random.default_rng(0)
        cin, cout, k = 3, 5, 3
        layer = ConvolutionLayer(n_out=cout, kernel_size=(k, k),
                                 stride=(stride, stride),
                                 convolution_mode=mode,
                                 activation="identity")
        params, state = _init(layer, cin)
        w = rng.standard_normal((k, k, cin, cout)).astype(np.float32) * 0.3
        b = rng.standard_normal(cout).astype(np.float32) * 0.1
        params = {**params, "W": w, "b": b}
        x = rng.standard_normal((2, 9, 9, cin)).astype(np.float32)
        got, _ = layer.forward(params, state, x)

        tconv = torch.nn.Conv2d(
            cin, cout, k, stride=stride,
            padding="same" if (mode == ConvolutionMode.SAME and stride == 1)
            else 0)
        with torch.no_grad():
            tconv.weight.copy_(torch.from_numpy(
                w.transpose(3, 2, 0, 1)))          # HWIO → OIHW
            tconv.bias.copy_(torch.from_numpy(b))
        xt = torch.from_numpy(x.transpose(0, 3, 1, 2))  # NHWC → NCHW
        if mode == ConvolutionMode.SAME and stride != 1:
            # torch 'same' only supports stride 1 — pad manually with
            # TF/XLA SAME arithmetic (pad_total split low/high)
            pad_total = max((int(np.ceil(9 / stride)) - 1) * stride + k - 9, 0)
            lo, hi = pad_total // 2, pad_total - pad_total // 2
            xt = torch.nn.functional.pad(xt, (lo, hi, lo, hi))
        want = tconv(xt).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)

    def test_maxpool_matches_torch(self):
        rng = np.random.default_rng(1)
        layer = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))
        params, state = _init(layer, 4)
        x = rng.standard_normal((2, 8, 8, 4)).astype(np.float32)
        got, _ = layer.forward({}, state, x)
        want = torch.nn.functional.max_pool2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), 2
        ).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


class TestDenseBatchNormParity:
    def test_dense_matches_torch(self):
        rng = np.random.default_rng(2)
        layer = DenseLayer(n_out=7, activation="tanh")
        params, state = _init(layer, 5)
        w = rng.standard_normal((5, 7)).astype(np.float32)
        b = rng.standard_normal(7).astype(np.float32)
        params = {**params, "W": w, "b": b}
        x = rng.standard_normal((4, 5)).astype(np.float32)
        got, _ = layer.forward(params, state, x)
        lin = torch.nn.Linear(5, 7)
        with torch.no_grad():
            lin.weight.copy_(torch.from_numpy(w.T))
            lin.bias.copy_(torch.from_numpy(b))
        want = torch.tanh(lin(torch.from_numpy(x))).detach().numpy()
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-5, atol=1e-6)

    def test_batchnorm_inference_matches_torch(self):
        rng = np.random.default_rng(3)
        C = 6
        layer = BatchNormalization(eps=1e-3)
        params, state = _init(layer, C)
        gamma = rng.standard_normal(C).astype(np.float32)
        beta = rng.standard_normal(C).astype(np.float32)
        mean = rng.standard_normal(C).astype(np.float32)
        var = rng.random(C).astype(np.float32) + 0.5
        params = {**params, "gamma": gamma, "beta": beta}
        state = {**state, "mean": mean, "var": var}
        x = rng.standard_normal((4, 5, 5, C)).astype(np.float32)
        got, _ = layer.forward(params, state, x, train=False)
        bn = torch.nn.BatchNorm2d(C, eps=1e-3)
        with torch.no_grad():
            bn.weight.copy_(torch.from_numpy(gamma))
            bn.bias.copy_(torch.from_numpy(beta))
            bn.running_mean.copy_(torch.from_numpy(mean))
            bn.running_var.copy_(torch.from_numpy(var))
        bn.eval()
        want = bn(torch.from_numpy(x.transpose(0, 3, 1, 2))
                  ).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)


class TestLSTMParity:
    def test_lstm_matches_torch(self):
        """Gate-order crosswalk: ours is IFOG, torch is IFGO (i,f,g,o
        with g=cell candidate); both use sigmoid gates + tanh."""
        rng = np.random.default_rng(4)
        F, U, T, B = 3, 5, 6, 2
        layer = LSTM(n_out=U, activation="tanh", gate_activation="sigmoid")
        params, state = _init(layer, F)
        W = rng.standard_normal((F, 4 * U)).astype(np.float32) * 0.4
        R = rng.standard_normal((U, 4 * U)).astype(np.float32) * 0.4
        b = rng.standard_normal(4 * U).astype(np.float32) * 0.1
        params = {**params, "W": W, "RW": R, "b": b}
        x = rng.standard_normal((B, T, F)).astype(np.float32)
        got, _ = layer.forward(params, state, x)   # [B, T, U]

        def ifog_to_ifgo(a, axis):
            i, f, o, g = np.split(a, 4, axis=axis)
            return np.concatenate([i, f, g, o], axis=axis)

        lstm = torch.nn.LSTM(F, U, batch_first=True)
        with torch.no_grad():
            lstm.weight_ih_l0.copy_(torch.from_numpy(ifog_to_ifgo(W, 1).T))
            lstm.weight_hh_l0.copy_(torch.from_numpy(ifog_to_ifgo(R, 1).T))
            lstm.bias_ih_l0.copy_(torch.from_numpy(ifog_to_ifgo(b, 0)))
            lstm.bias_hh_l0.zero_()
        want, _ = lstm(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestMoreLayerParity:
    def test_separable_conv_matches_torch(self):
        """Depthwise (groups=cin) + pointwise 1x1, depth_multiplier=2,
        in-major depthwise channel layout."""
        from deeplearning4j_tpu.nn.layers import SeparableConvolution2D
        rng = np.random.default_rng(5)
        cin, dm, cout, k = 3, 2, 5, 3
        layer = SeparableConvolution2D(n_out=cout, kernel_size=(k, k),
                                       depth_multiplier=dm,
                                       convolution_mode=ConvolutionMode.TRUNCATE,
                                       activation="identity")
        params, state = _init(layer, cin)
        dk = rng.standard_normal((k, k, cin, dm)).astype(np.float32) * 0.3
        pk = rng.standard_normal((1, 1, cin * dm, cout)).astype(np.float32)
        b = rng.standard_normal(cout).astype(np.float32) * 0.1
        params = {**params, "dW": dk, "pW": pk, "b": b}
        x = rng.standard_normal((2, 7, 7, cin)).astype(np.float32)
        got, _ = layer.forward(params, state, x)

        dw = torch.nn.Conv2d(cin, cin * dm, k, groups=cin, bias=False)
        pw = torch.nn.Conv2d(cin * dm, cout, 1)
        with torch.no_grad():
            # HWI(dm) in-major -> torch [cin*dm, 1, k, k] grouped layout
            dw.weight.copy_(torch.from_numpy(
                dk.transpose(2, 3, 0, 1).reshape(cin * dm, 1, k, k)))
            pw.weight.copy_(torch.from_numpy(pk[0, 0].T[:, :, None, None]))
            pw.bias.copy_(torch.from_numpy(b))
        xt = torch.from_numpy(x.transpose(0, 3, 1, 2))
        want = pw(dw(xt)).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)

    def test_lrn_matches_torch(self):
        from deeplearning4j_tpu.nn.layers import LocalResponseNormalization
        rng = np.random.default_rng(6)
        C = 8
        layer = LocalResponseNormalization(k=2.0, n=5, alpha=1e-3, beta=0.75)
        params, state = _init(layer, C)
        x = rng.standard_normal((2, 6, 6, C)).astype(np.float32)
        got, _ = layer.forward({}, state, x)
        # torch divides alpha by n inside; ours follows the reference
        # (alpha applied to the raw window sum) -> scale alpha up
        want = torch.nn.functional.local_response_norm(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), size=5,
            alpha=1e-3 * 5, beta=0.75, k=2.0
        ).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)

    def test_layernorm_matches_torch(self):
        from deeplearning4j_tpu.nn.layers import LayerNormalization
        rng = np.random.default_rng(7)
        F = 10
        layer = LayerNormalization(eps=1e-5)
        params, state = _init(layer, F)
        g = rng.standard_normal(F).astype(np.float32)
        b = rng.standard_normal(F).astype(np.float32)
        params = {**params, "gamma": g, "beta": b}
        x = rng.standard_normal((4, F)).astype(np.float32)
        got, _ = layer.forward(params, state, x)
        ln = torch.nn.LayerNorm(F, eps=1e-5)
        with torch.no_grad():
            ln.weight.copy_(torch.from_numpy(g))
            ln.bias.copy_(torch.from_numpy(b))
        want = ln(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)

    def test_attention_matches_torch(self):
        """Full MHA block vs torch.nn.MultiheadAttention — validates
        the XLA attention path's QKV projection layout, scaling, and
        softmax semantics end-to-end."""
        from deeplearning4j_tpu.nn.layers import MultiHeadAttention
        rng = np.random.default_rng(8)
        D, H, T, B = 8, 2, 5, 2
        # pin the XLA path: on a TPU host auto mode would route through
        # the Pallas flash kernel instead of the einsum/softmax path
        # this test is about
        layer = MultiHeadAttention(n_out=D, n_heads=H, has_bias=True,
                                   activation="identity", use_flash=False)
        params, state = _init(layer, D)
        ws = {n: rng.standard_normal((D, D)).astype(np.float32) * 0.3
              for n in ("Wq", "Wk", "Wv", "Wo")}
        bs = {f"b{n[1:]}": rng.standard_normal(D).astype(np.float32) * 0.1
              for n in ("Wq", "Wk", "Wv", "Wo")}
        params = {**params, **ws, **bs}
        x = rng.standard_normal((B, T, D)).astype(np.float32)
        got, _ = layer.forward(params, state, x)

        mha = torch.nn.MultiheadAttention(D, H, batch_first=True)
        with torch.no_grad():
            mha.in_proj_weight.copy_(torch.from_numpy(np.concatenate(
                [ws["Wq"].T, ws["Wk"].T, ws["Wv"].T], axis=0)))
            mha.in_proj_bias.copy_(torch.from_numpy(np.concatenate(
                [bs["bq"], bs["bk"], bs["bv"]])))
            mha.out_proj.weight.copy_(torch.from_numpy(ws["Wo"].T))
            mha.out_proj.bias.copy_(torch.from_numpy(bs["bo"]))
        xt = torch.from_numpy(x)
        want, _ = mha(xt, xt, xt, need_weights=False)
        np.testing.assert_allclose(np.asarray(got), want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestRecurrentParityMore:
    def test_graves_bilstm_matches_two_torch_lstms(self):
        """Peephole weights init to ZERO, so each direction reduces to a
        standard LSTM: fwd torch LSTM + reversed torch LSTM, outputs
        summed (the reference's activateOutput combination)."""
        from deeplearning4j_tpu.nn.layers import GravesBidirectionalLSTM
        rng = np.random.default_rng(9)
        F, U, T, B = 3, 4, 5, 2
        layer = GravesBidirectionalLSTM(n_out=U, activation="tanh",
                                        gate_activation="sigmoid")
        params, state = _init(layer, F)

        def mk(suffix):
            W = rng.standard_normal((F, 4 * U)).astype(np.float32) * 0.4
            R = rng.standard_normal((U, 4 * U)).astype(np.float32) * 0.4
            b = rng.standard_normal(4 * U).astype(np.float32) * 0.1
            return {f"W{suffix}": W, f"RW{suffix}": R, f"b{suffix}": b}

        pf, pb = mk("F"), mk("B")
        params = {**params, **pf, **pb}
        x = rng.standard_normal((B, T, F)).astype(np.float32)
        got, _ = layer.forward(params, state, x)

        def ifog_to_ifgo(a, axis):
            i, f, o, g = np.split(a, 4, axis=axis)
            return np.concatenate([i, f, g, o], axis=axis)

        def torch_dir(p, suffix, reverse):
            lstm = torch.nn.LSTM(F, U, batch_first=True)
            with torch.no_grad():
                lstm.weight_ih_l0.copy_(torch.from_numpy(
                    ifog_to_ifgo(p[f"W{suffix}"], 1).T))
                lstm.weight_hh_l0.copy_(torch.from_numpy(
                    ifog_to_ifgo(p[f"RW{suffix}"], 1).T))
                lstm.bias_ih_l0.copy_(torch.from_numpy(
                    ifog_to_ifgo(p[f"b{suffix}"], 0)))
                lstm.bias_hh_l0.zero_()
            xt = torch.from_numpy(x[:, ::-1].copy() if reverse else x)
            out, _ = lstm(xt)
            out = out.detach().numpy()
            return out[:, ::-1] if reverse else out

        want = torch_dir(pf, "F", False) + torch_dir(pb, "B", True)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)

    def test_conv1d_matches_torch(self):
        from deeplearning4j_tpu.nn.layers import Convolution1DLayer
        rng = np.random.default_rng(10)
        cin, cout, k, T = 4, 6, 3, 9
        layer = Convolution1DLayer(n_out=cout, kernel_size=k, stride=1,
                                   convolution_mode=ConvolutionMode.TRUNCATE,
                                   activation="identity")
        params, state = _init(layer, cin)
        w = rng.standard_normal((k, 1, cin, cout)).astype(np.float32) * 0.3
        b = rng.standard_normal(cout).astype(np.float32) * 0.1
        params = {**params, "W": w, "b": b}
        x = rng.standard_normal((2, T, cin)).astype(np.float32)
        got, _ = layer.forward(params, state, x)
        tconv = torch.nn.Conv1d(cin, cout, k)
        with torch.no_grad():
            tconv.weight.copy_(torch.from_numpy(
                w[:, 0].transpose(2, 1, 0)))        # kIC→OIk
            tconv.bias.copy_(torch.from_numpy(b))
        want = tconv(torch.from_numpy(x.transpose(0, 2, 1))
                     ).detach().numpy().transpose(0, 2, 1)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)
