"""MultiLayerNetwork container tests: end-to-end training, serde,
masking, TBPTT, streaming inference — mirrors the reference's
MultiLayerTest / BackPropMLPTest / MultiLayerTestRNN."""

import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.common.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator, load_iris
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.builder import BackpropType, MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import (
    LSTM,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresListener


def iris_mlp_conf(updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(updater or Adam(0.02))
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax", loss="mcxent"))
            .build())


class TestTraining:
    def test_iris_learns(self):
        x, y = load_iris()
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        listener = CollectScoresListener()
        net.set_listeners(listener)
        net.fit(x, y, epochs=30, batch_size=50)
        e = net.evaluate(ArrayDataSetIterator(x, y, batch_size=150))
        assert e.accuracy() > 0.9, e.stats()
        first_score = listener.scores[0][1]
        last_score = listener.scores[-1][1]
        assert last_score < first_score * 0.5

    def test_score_decreases_xor(self):
        x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], dtype=np.float32)
        y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], dtype=np.float32)
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.1)).list()
                .layer(DenseLayer(n_in=2, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y, epochs=200, batch_size=4, shuffle=False)
        out = np.asarray(net.output(x))
        assert np.all(np.argmax(out, 1) == np.argmax(y, 1))

    def test_output_shape_and_softmax(self):
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        out = np.asarray(net.output(np.random.randn(5, 4).astype(np.float32)))
        assert out.shape == (5, 3)
        np.testing.assert_allclose(out.sum(axis=1), np.ones(5), atol=1e-5)

    def test_num_params(self):
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        assert net.num_params() == 4 * 16 + 16 + 16 * 3 + 3

    def test_param_table_keys(self):
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        assert set(net.param_table()) == {"0_W", "0_b", "1_W", "1_b"}

    def test_fit_with_iterator_and_listeners(self):
        it = IrisDataSetIterator(batch_size=32)
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        scores = CollectScoresListener()
        net.set_listeners(scores)
        net.fit(it, epochs=3)
        assert net.iteration_count == 3 * 5  # 150/32 → 5 batches
        assert net.epoch_count == 3
        assert len(scores.scores) == 15

    def test_cnn_smoke(self):
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2)).list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.randn(8, 8, 8, 1).astype(np.float32)
        y = np.eye(2)[np.random.randint(0, 2, 8)].astype(np.float32)
        s0 = None
        net.fit(x, y, epochs=10, batch_size=8, shuffle=False)
        assert np.isfinite(net.score())

    def test_nchw_data_format(self):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3)))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(6, 6, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        x_nchw = np.random.randn(4, 2, 6, 6).astype(np.float32)
        out = net.output(x_nchw, data_format="NCHW")
        assert out.shape == (4, 2)
        # same data in native NHWC gives identical results
        out2 = net.output(np.transpose(x_nchw, (0, 2, 3, 1)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


class TestRnn:
    def _rnn_conf(self, tbptt=False):
        b = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2)).list()
             .layer(LSTM(n_in=5, n_out=8))
             .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax", loss="mcxent")))
        if tbptt:
            b = b.backprop_type(BackpropType.TRUNCATED_BPTT, 4)
        return b.build()

    def test_rnn_fit_and_output(self):
        net = MultiLayerNetwork(self._rnn_conf()).init()
        x = np.random.randn(4, 10, 5).astype(np.float32)
        y = np.eye(3)[np.random.randint(0, 3, (4, 10))].astype(np.float32)
        net.fit(x, y, epochs=3, batch_size=4)
        out = net.output(x)
        assert out.shape == (4, 10, 3)

    def test_tbptt_runs(self):
        net = MultiLayerNetwork(self._rnn_conf(tbptt=True)).init()
        x = np.random.randn(2, 12, 5).astype(np.float32)
        y = np.eye(3)[np.random.randint(0, 3, (2, 12))].astype(np.float32)
        net.fit(x, y, epochs=2, batch_size=2)
        assert np.isfinite(net.score())

    def test_variable_length_masking(self):
        """Masked steps must not change the loss (reference
        TestVariableLengthTS idea)."""
        net = MultiLayerNetwork(self._rnn_conf()).init()
        x_short = np.random.randn(2, 3, 5).astype(np.float32)
        y_short = np.eye(3)[np.random.randint(0, 3, (2, 3))].astype(np.float32)
        # pad to length 6 with garbage + mask
        x_pad = np.concatenate([x_short, 99 * np.ones((2, 3, 5), np.float32)], axis=1)
        y_pad = np.concatenate([y_short, np.zeros((2, 3, 3), np.float32)], axis=1)
        mask = np.concatenate([np.ones((2, 3)), np.zeros((2, 3))], axis=1).astype(np.float32)
        s_short = net.score(DataSet(x_short, y_short))
        s_pad = net.score(DataSet(x_pad, y_pad, features_mask=mask, labels_mask=mask))
        np.testing.assert_allclose(s_short, s_pad, rtol=1e-5)

    def test_rnn_time_step_matches_full_forward(self):
        """Streaming rnnTimeStep == full-sequence forward (reference
        MultiLayerTestRNN.testRnnTimeStep)."""
        net = MultiLayerNetwork(self._rnn_conf()).init()
        x = np.random.randn(2, 6, 5).astype(np.float32)
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        stream = []
        for t in range(6):
            stream.append(np.asarray(net.rnn_time_step(x[:, t, :])))
        stream = np.stack(stream, axis=1)
        np.testing.assert_allclose(full, stream, atol=1e-5)

    def test_nft_data_format(self):
        net = MultiLayerNetwork(self._rnn_conf()).init()
        x = np.random.randn(2, 6, 5).astype(np.float32)
        x_nft = np.transpose(x, (0, 2, 1))  # [B,F,T] reference layout
        out_native = np.asarray(net.output(x))
        out_nft = np.asarray(net.output(x_nft, data_format="NFT"))
        np.testing.assert_allclose(out_native, out_nft, atol=1e-6)


class TestConfSerde:
    def test_multilayer_conf_json_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(2e-3))
                .l2(1e-4).list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5), activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(BatchNormalization())
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        js = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(js)
        assert conf2.to_json() == js
        # same params from same seed
        n1 = MultiLayerNetwork(conf).init()
        n2 = MultiLayerNetwork(conf2).init()
        for k in n1.param_table():
            np.testing.assert_allclose(np.asarray(n1.param_table()[k]),
                                       np.asarray(n2.param_table()[k]))

    def test_dropout_not_applied_at_inference(self):
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.randn(3, 4).astype(np.float32)
        o1 = np.asarray(net.output(x))
        o2 = np.asarray(net.output(x))
        np.testing.assert_allclose(o1, o2)


class TestStepsPerExecution:
    """steps_per_execution fuses k steps into one lax.scan dispatch —
    the loss trajectory must be bit-comparable to per-step dispatch."""

    def _trajectory(self, spe, with_bn=False):
        x, y = load_iris()
        layers = [DenseLayer(n_in=4, n_out=16, activation="relu")]
        if with_bn:
            layers.append(BatchNormalization(n_out=16))
        layers.append(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                  loss="mcxent"))
        b = (NeuralNetConfiguration.builder().seed(42).updater(Adam(0.02))
             .list())
        for l in layers:
            b.layer(l)
        net = MultiLayerNetwork(b.build()).init()
        listener = CollectScoresListener()
        net.set_listeners(listener)
        net.fit(x, y, epochs=4, batch_size=50, shuffle=False,
                steps_per_execution=spe)
        return [s for _, s in listener.scores], net

    def test_fused_matches_per_step(self):
        ref, net1 = self._trajectory(1)
        fused, net4 = self._trajectory(4)
        assert len(ref) == len(fused) == 12
        np.testing.assert_allclose(fused, ref, rtol=2e-5, atol=1e-6)
        for k in net1.param_table():
            np.testing.assert_allclose(np.asarray(net4.param_table()[k]),
                                       np.asarray(net1.param_table()[k]),
                                       rtol=2e-4, atol=1e-5)

    def test_fused_with_batchnorm_state(self):
        ref, _ = self._trajectory(1, with_bn=True)
        fused, _ = self._trajectory(3, with_bn=True)
        np.testing.assert_allclose(fused, ref, rtol=2e-5, atol=1e-6)

    def test_ragged_tail_and_shape_change(self):
        # 150 examples / batch 40 -> 3 full + 1 ragged batch per epoch;
        # fused path must flush the ragged tail through the single-step path
        x, y = load_iris()
        net = MultiLayerNetwork(iris_mlp_conf()).init()
        listener = CollectScoresListener()
        net.set_listeners(listener)
        net.fit(x, y, epochs=2, batch_size=40, shuffle=False,
                steps_per_execution=4)
        assert len(listener.scores) == 8
        assert net.iteration_count == 8
