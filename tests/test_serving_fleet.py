"""Fleet serving: versioned ModelRegistry, zero-downtime hot-swap,
router request plane, autoscaling.

The spine of this suite is the VERSION-TAGGED parity contract
(docs/SERVING.md "Fleet"): during a hot-swap, streams admitted on
version v finish bit-equal to an unswapped v reference (they complete
on the old weights), post-swap admissions are bit-equal to the v+1
reference, and ZERO streams are dropped or reset — plus the registry
durability contracts (one-winner publish, corrupt-zip fallback,
retention that never collects the served version).
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.fault.errors import CheckpointCorruptError
from deeplearning4j_tpu.serving import (
    FleetAutoscaler,
    FleetClient,
    FleetRouter,
    FleetServer,
    GenerationServer,
    ModelRegistry,
    ServerDrainingError,
    ServerStoppedError,
    ShedError,
    UnknownModelError,
    VersionConflictError,
)
from deeplearning4j_tpu.zoo.transformer import TransformerLM, generate

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 16
BL = 4


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net_v1():
    return tiny_lm(seed=3)


@pytest.fixture(scope="module")
def net_v2():
    return tiny_lm(seed=9)


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (8, 3))


@pytest.fixture(scope="module")
def ref_v1(net_v1, prompts):
    return generate(net_v1, prompts, 6, temperature=0)


@pytest.fixture(scope="module")
def ref_v2(net_v2, prompts):
    return generate(net_v2, prompts, 6, temperature=0)


def tiny_mlp(seed=7):
    """Cheap non-transformer model for registry-only tests."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _corrupt(path, offset_frac=0.5, n=64):
    data = bytearray(path.read_bytes())
    mid = int(len(data) * offset_frac)
    for i in range(mid, min(mid + n, len(data))):
        data[i] ^= 0xFF
    path.write_bytes(data)


def _params_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a.params)
    lb = jax.tree_util.tree_leaves(b.params)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ======================================================== ModelRegistry
class TestModelRegistry:
    def test_publish_resolve_roundtrip(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_mlp()
        v = reg.publish("mlp", net)
        assert v == 1
        assert reg.versions("mlp") == [1]
        assert reg.models() == ["mlp"]
        restored, rv = reg.resolve("mlp")
        assert rv == 1 and _params_equal(restored, net)
        x = np.random.default_rng(0).standard_normal((4, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(restored.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_auto_versions_monotonic(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        net = tiny_mlp()
        assert [reg.publish("m", net) for _ in range(3)] == [1, 2, 3]
        assert reg.latest("m") == 3

    def test_explicit_version_conflict_one_winner(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        a, b = tiny_mlp(seed=1), tiny_mlp(seed=2)
        reg.publish("m", a, version=7)
        with pytest.raises(VersionConflictError, match="v7"):
            reg.publish("m", b, version=7)
        restored, _ = reg.resolve("m", 7)
        assert _params_equal(restored, a) and not _params_equal(restored, b)

    def test_concurrent_same_version_exactly_one_winner(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        nets = [tiny_mlp(seed=s) for s in (1, 2, 3, 4)]
        outcomes = [None] * 4
        barrier = threading.Barrier(4)

        def pub(i):
            barrier.wait()
            try:
                reg.publish("m", nets[i], version=5)
                outcomes[i] = "won"
            except VersionConflictError:
                outcomes[i] = "lost"
            except Exception as e:  # noqa: BLE001 — a loser crashing
                # any other way (e.g. its tmp GC'd mid-claim) breaks
                # the one-winner contract
                outcomes[i] = f"crashed: {e!r}"

        threads = [threading.Thread(target=pub, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # every publisher gets a DEFINED outcome: one winner, the
        # rest the NAMED conflict error — never a crash
        assert outcomes.count("won") == 1, outcomes
        assert outcomes.count("lost") == 3, outcomes
        winner = nets[outcomes.index("won")]
        restored, _ = reg.resolve("m", 5)
        assert _params_equal(restored, winner)
        # no tmp orphans left behind
        assert not list(reg.model_dir("m").glob(".publish-*"))

    def test_corrupt_latest_falls_back_with_warning(self, tmp_path,
                                                    caplog):
        reg = ModelRegistry(tmp_path)
        a, b = tiny_mlp(seed=1), tiny_mlp(seed=2)
        reg.publish("m", a)
        reg.publish("m", b)
        _corrupt(reg.path("m", 2))
        import logging
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.serving.registry"):
            restored, v = reg.resolve("m")
        assert v == 1 and _params_equal(restored, a)
        assert any("corrupt" in r.message and "falling back" in r.message
                   for r in caplog.records)

    def test_explicit_corrupt_version_raises(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        reg.publish("m", tiny_mlp(seed=1))
        reg.publish("m", tiny_mlp(seed=2))
        _corrupt(reg.path("m", 2))
        # an explicit pin must fail hard, never silently substitute
        with pytest.raises(CheckpointCorruptError):
            reg.resolve("m", 2)
        # latest still works via fallback
        assert reg.resolve("m")[1] == 1

    def test_all_corrupt_raises_naming_candidates(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        reg.publish("m", tiny_mlp(seed=1))
        reg.publish("m", tiny_mlp(seed=2))
        _corrupt(reg.path("m", 1))
        _corrupt(reg.path("m", 2))
        with pytest.raises(CheckpointCorruptError) as ei:
            reg.resolve("m")
        assert "v1" in str(ei.value) and "v2" in str(ei.value)

    def test_retention_keep_last_and_keep_every(self, tmp_path):
        reg = ModelRegistry(tmp_path, keep_last=2, keep_every=3)
        net = tiny_mlp()
        for _ in range(7):
            reg.publish("m", net)
        # newest 2 {6,7} + every 3rd {3,6}
        assert reg.versions("m") == [3, 6, 7]

    def test_retention_never_deletes_pinned(self, tmp_path):
        reg = ModelRegistry(tmp_path, keep_last=1)
        net = tiny_mlp()
        reg.publish("m", net)
        reg.pin("m", 1)           # the currently-served version
        for _ in range(4):
            reg.publish("m", net)
        assert 1 in reg.versions("m")        # survived 4 GC passes
        assert reg.versions("m") == [1, 5]
        reg.unpin("m", 1)                    # unpin sweeps
        assert reg.versions("m") == [5]

    def test_pin_markers_protect_across_registry_instances(self,
                                                           tmp_path):
        """The checkpoint-as-publish layout: a trainer PROCESS runs
        retention over the same root a serving process reads — its
        in-memory pin set is empty, so the serving process's pins must
        ride on-disk markers or GC deletes live weights."""
        serving = ModelRegistry(tmp_path, keep_last=1)
        net = tiny_mlp()
        serving.publish("m", net)
        serving.pin("m", 1)                   # the served version
        # the "trainer process": a separate instance, no in-memory pins
        trainer = ModelRegistry(tmp_path, keep_last=1)
        for _ in range(3):
            trainer.publish("m", net)
        assert 1 in trainer.versions("m")     # marker protected it
        serving.unpin("m", 1)
        trainer.publish("m", net)
        assert 1 not in trainer.versions("m")

    def test_stale_pin_marker_from_dead_pid_is_swept(self, tmp_path):
        reg = ModelRegistry(tmp_path, keep_last=1)
        net = tiny_mlp()
        reg.publish("m", net)
        # forge a marker from a long-dead process
        (reg.model_dir("m") / ".pin-v1.999999999").touch()
        for _ in range(2):
            reg.publish("m", net)
        assert reg.versions("m") == [3]       # stale marker ignored
        assert not list(reg.model_dir("m").glob(".pin-v1.*"))

    def test_resolve_missing(self, tmp_path):
        reg = ModelRegistry(tmp_path)
        with pytest.raises(FileNotFoundError, match="no published"):
            reg.resolve("ghost")
        reg.publish("m", tiny_mlp())
        with pytest.raises(FileNotFoundError, match="v9"):
            reg.resolve("m", 9)
        with pytest.raises(ValueError, match="invalid model name"):
            reg.publish("../escape", tiny_mlp())

    def test_publish_listener_checkpoint_as_publish(self, tmp_path):
        """The one-liner: attach `registry.publish_listener(...)` to a
        fit loop and every N steps becomes a served release."""
        reg = ModelRegistry(tmp_path)
        net = tiny_mlp()
        listener = reg.publish_listener("mlp", frequency=4)
        net.add_listener(listener)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.fit(x, y, epochs=1, batch_size=4)            # 8 steps
        assert len(listener.published_versions) >= 2     # step 4, 8
        restored, v = reg.resolve("mlp")
        assert v == reg.latest("mlp")
        # the final publish carries the POST-fit params
        assert _params_equal(restored, net)


# ====================================================== drain lifecycle
class TestDrainAndLifecycle:
    def test_drain_finishes_inflight_blocks_admissions(self, net_v1,
                                                       prompts, ref_v1):
        srv = GenerationServer(net_v1, n_slots=2, n_blocks=16,
                               block_len=BL).start()
        try:
            streams = [srv.generate_async(prompts[i], 6)
                       for i in range(4)]
            assert srv.drain(timeout=120) is True
            # every already-submitted stream finished, bit-equal
            got = np.stack([s.result(timeout=0) for s in streams])
            np.testing.assert_array_equal(got, ref_v1[:4])
            assert srv.open_streams == 0
            # admissions are closed with the NAMED error
            with pytest.raises(ServerDrainingError):
                srv.generate_async(prompts[0], 6)
        finally:
            srv.stop()

    def test_drain_idle_server_immediate(self, net_v1):
        srv = GenerationServer(net_v1, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        try:
            assert srv.drain(timeout=5) is True
        finally:
            srv.stop()

    def test_stop_idempotent(self, net_v1, prompts):
        srv = GenerationServer(net_v1, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        s = srv.generate_async(prompts[0], 6)
        srv.stop()
        srv.stop()                       # second stop: clean no-op
        srv.shutdown()                   # and shutdown after stop too
        with pytest.raises(RuntimeError):
            s.result(timeout=10)

    def test_start_after_stop_raises_named_error(self, net_v1):
        srv = GenerationServer(net_v1, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        srv.stop()
        with pytest.raises(ServerStoppedError, match="fresh server"):
            srv.start()
        # and the scheduler thread was NOT restarted by the attempt
        assert srv._collector is None and not srv._running


# ============================================================= hot-swap
class TestHotSwap:
    def test_swap_zero_drop_version_parity(self, tmp_path, net_v1,
                                           net_v2, prompts, ref_v1,
                                           ref_v2):
        """The fleet acceptance drill at test scale: in-flight v1
        streams finish bit-equal to the unswapped v1 reference,
        post-swap admissions match v2, nothing drops."""
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        try:
            assert fleet.deploy("lm", n_slots=2, n_blocks=16,
                                block_len=BL) == 1
            pre = [router.submit("lm", prompts[i], 6) for i in range(6)]
            assert {s.version for s in pre} == {1}
            reg.publish("lm", net_v2)
            assert fleet.swap("lm") == 2
            post = [router.submit("lm", prompts[i], 6)
                    for i in range(6)]
            assert {s.version for s in post} == {2}
            got_pre = np.stack([s.result(timeout=120) for s in pre])
            got_post = np.stack([s.result(timeout=120) for s in post])
        finally:
            fleet.stop()
        np.testing.assert_array_equal(got_pre, ref_v1[:6])
        np.testing.assert_array_equal(got_post, ref_v2[:6])

    def test_swap_pins_served_and_unpins_old(self, tmp_path, net_v1,
                                             net_v2):
        reg = ModelRegistry(tmp_path, keep_last=1)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            assert ("lm", 1) in reg.pinned()
            # keep_last=1 would GC v1 on the v2 publish — the pin is
            # what keeps the SERVED version's zip alive
            reg.publish("lm", net_v2)
            assert 1 in reg.versions("lm")
            fleet.swap("lm")
            assert ("lm", 2) in reg.pinned()
            assert ("lm", 1) not in reg.pinned()
            # unpinned v1 is collectable now
            assert reg.versions("lm") == [2]
        finally:
            fleet.stop()
        assert reg.pinned() == set()

    def test_scale_resize_keeps_parity(self, tmp_path, net_v1, prompts,
                                       ref_v1):
        """Autoscale's primitive: same-version resize through the swap
        machinery — streams before and after all parity-exact."""
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            pre = [router.submit("lm", prompts[i], 6) for i in range(3)]
            rec = fleet.scale("lm", n_slots=4, n_blocks=16)
            assert rec["before"]["n_slots"] == 1
            assert rec["after"]["n_slots"] == 4
            assert fleet.server("lm").engine.n_slots == 4
            assert fleet.version("lm") == 1          # same weights
            post = [router.submit("lm", prompts[3 + i], 6)
                    for i in range(3)]
            got = np.stack([s.result(timeout=120)
                            for s in pre + post])
        finally:
            fleet.stop()
        np.testing.assert_array_equal(got, ref_v1[:6])

    def test_deploy_duplicate_and_swap_unknown(self, tmp_path, net_v1):
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            with pytest.raises(ValueError, match="already deployed"):
                fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            with pytest.raises(KeyError, match="ghost"):
                fleet.swap("ghost")
        finally:
            fleet.stop()


# =============================================================== router
class TestFleetRouter:
    def test_unknown_model_names_known(self, tmp_path, net_v1):
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            with pytest.raises(UnknownModelError, match="lm"):
                router.submit("ghost", np.zeros(3, np.int32), 4)
        finally:
            fleet.stop()

    def test_weighted_shedding(self, tmp_path, net_v1, prompts):
        """Fleet-wide pressure: the low-weight model's projected delay
        exceeds ITS weighted budget while the high-weight model keeps
        admitting — weighted SLO shedding across models."""
        reg = ModelRegistry(tmp_path)
        reg.publish("hi", net_v1)
        reg.publish("lo", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet, slo_ttft_s=0.05,
                             weights={"hi": 1e6, "lo": 1e-9})
        try:
            fleet.deploy("hi", n_slots=1, n_blocks=8, block_len=BL)
            fleet.deploy("lo", n_slots=1, n_blocks=8, block_len=BL)
            # prime both EWMA estimators
            for n in ("hi", "lo"):
                router.submit(n, prompts[0], 6).result(timeout=120)

            def flood(name, k=6):
                streams, sheds = [], 0
                for i in range(k):
                    try:
                        streams.append(
                            router.submit(name, prompts[i % 8], 12))
                    except ShedError as e:
                        assert "weighted" in str(e)
                        sheds += 1
                return streams, sheds

            # the SAME burst against both models: hi's budget
            # (slo * 1e6 seconds) is unmissable, lo's (slo * 1e-9)
            # unmeetable once anything is outstanding — low-weight
            # models shed first under identical pressure
            hi_streams, hi_sheds = flood("hi")
            lo_streams, lo_sheds = flood("lo")
            assert hi_sheds == 0 and len(hi_streams) == 6
            assert lo_sheds >= 1
            for s in hi_streams + lo_streams:
                s.result(timeout=120)
        finally:
            fleet.stop()

    def test_max_queue_backstop(self, tmp_path, net_v1, prompts):
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet, max_queue=1)
        try:
            # pool fits ONE sequence: later submits queue
            fleet.deploy("lm", n_slots=4, n_blocks=4, block_len=BL)
            streams = [router.submit("lm", prompts[0], 6)]
            shed = 0
            for _ in range(8):
                try:
                    streams.append(router.submit("lm", prompts[0], 6))
                except ShedError:
                    shed += 1
            for s in streams:
                s.result(timeout=120)
        finally:
            fleet.stop()
        assert shed >= 1


# ======================================================== request plane
class TestRequestPlane:
    def test_wire_roundtrip(self):
        from deeplearning4j_tpu.serving import wire
        prompt = np.arange(5, dtype=np.int64)
        data = wire.encode_request("lm", "rid1", prompt, 8,
                                   temperature=0.5, top_p=0.9,
                                   rng=np.asarray([1, 2], np.uint32))
        header, p = wire.decode_request(data)
        np.testing.assert_array_equal(p, prompt)
        assert header["model"] == "lm" and header["n_tokens"] == 8
        assert header["temperature"] == 0.5 and header["top_p"] == 0.9
        np.testing.assert_array_equal(header["rng"],
                                      np.asarray([1, 2], np.uint32))
        rep = wire.encode_reply("rid1", 3, [7, 8, 9], done=True,
                                model="lm", version=2)
        rh, toks = wire.decode_reply(rep)
        assert rh["seq"] == 3 and rh["done"] and rh["version"] == 2
        np.testing.assert_array_equal(toks, [7, 8, 9])
        assert wire.reply_error(rh) is None
        # error rehydration preserves the shed/failure split
        rep = wire.encode_reply("rid1", 0, None, done=True,
                                error=ShedError("too busy"))
        rh, _ = wire.decode_reply(rep)
        assert isinstance(wire.reply_error(rh), ShedError)
        with pytest.raises(ValueError, match="DLFQ"):
            wire.decode_request(rep)

    def test_remote_client_end_to_end(self, tmp_path, net_v1, prompts,
                                      ref_v1):
        """Clients hold a transport, never a server reference: request
        + streamed tokens ride the ndarray wire format end to end."""
        from deeplearning4j_tpu.streaming import LocalQueueTransport
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        tr = LocalQueueTransport()
        router = FleetRouter(fleet, transport=tr)
        try:
            fleet.deploy("lm", n_slots=2, n_blocks=16, block_len=BL)
            router.serve()
            client = FleetClient(tr)
            remote = [client.generate("lm", prompts[i], 6)
                      for i in range(4)]
            got = np.stack([r.result(timeout=120) for r in remote])
            np.testing.assert_array_equal(got, ref_v1[:4])
            assert {r.version for r in remote} == {1}
            # iterator face streams too
            it = list(client.generate("lm", prompts[0], 6))
            assert it == list(ref_v1[0])
            # unknown model fails remotely with the router's error
            bad = client.generate("ghost", prompts[0], 4)
            with pytest.raises(RuntimeError, match="ghost"):
                bad.result(timeout=60)
        finally:
            router.stop()
            fleet.stop()

    def test_remote_shed_crosses_wire_as_shed(self, tmp_path, net_v1,
                                              prompts):
        from deeplearning4j_tpu.streaming import LocalQueueTransport
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        tr = LocalQueueTransport()
        router = FleetRouter(fleet, transport=tr, max_queue=0)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            router.serve()
            remote = FleetClient(tr).generate("lm", prompts[0], 6)
            with pytest.raises(ShedError):
                remote.result(timeout=60)
        finally:
            router.stop()
            fleet.stop()


# =========================================================== autoscaler
class TestAutoscaler:
    def test_scales_up_on_queue_pressure_zero_drop(self, tmp_path,
                                                   net_v1, prompts,
                                                   ref_v1):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        monitor.enable(registry=MetricsRegistry())
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        scaler = FleetAutoscaler(fleet, queue_depth_high=2, factor=4,
                                 max_slots=4, max_blocks=32)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            # a backlog deeper than queue_depth_high
            streams = [router.submit("lm", prompts[i % 8], 6)
                       for i in range(8)]
            fleet.publish_gauges()       # the decision's signal plane
            made = scaler.check()
            assert len(made) == 1
            assert made[0]["after"]["n_slots"] == 4
            assert "queue_depth" in made[0]["reason"]
            assert fleet.server("lm").engine.n_slots == 4
            # the resize dropped nothing and kept parity
            got = np.stack([s.result(timeout=120) for s in streams])
            np.testing.assert_array_equal(
                got, np.stack([ref_v1[i % 8] for i in range(8)]))
            # cap respected: pressure again cannot exceed max_slots
            fleet.publish_gauges()
            assert scaler.check() == []
        finally:
            fleet.stop()
            monitor.disable()

    def test_idle_fleet_never_scales(self, tmp_path, net_v1):
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        scaler = FleetAutoscaler(fleet, queue_depth_high=2)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            assert scaler.check() == []
            assert fleet.server("lm").engine.n_slots == 1
        finally:
            fleet.stop()


# ===================================== queue-depth seam + rules mode
class TestQueueDepthSeam:
    def test_public_queue_depth_counts_pending_work(self, net_v1,
                                                    prompts):
        srv = GenerationServer(net_v1, n_slots=1, n_blocks=8,
                               block_len=BL).start()
        try:
            # one slot: at most one stream is ever in flight, so right
            # after a 6-request burst >= 4 submissions are still
            # awaiting admission — visible through the public seam
            streams = [srv.generate_async(prompts[i], 12)
                       for i in range(6)]
            assert srv.queue_depth() >= 4
            for s in streams:
                s.result(timeout=120)
        finally:
            srv.stop()
        assert srv.queue_depth() == 0

    def test_live_autoscaler_path_monitoring_off(self, tmp_path, net_v1,
                                                 prompts, ref_v1):
        """The live fallback reads the public seam, not scheduler
        internals — backlog pressure must scale with monitoring
        DISABLED (no gauges to read)."""
        from deeplearning4j_tpu import monitor
        assert not monitor.is_enabled()
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        scaler = FleetAutoscaler(fleet, queue_depth_high=2, factor=4,
                                 max_slots=4, max_blocks=32)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            streams = [router.submit("lm", prompts[i % 8], 6)
                       for i in range(8)]
            made = scaler.check()
            assert len(made) == 1
            assert "queue_depth" in made[0]["reason"]
            assert fleet.server("lm").engine.n_slots == 4
            got = np.stack([s.result(timeout=120) for s in streams])
            np.testing.assert_array_equal(
                got, np.stack([ref_v1[i % 8] for i in range(8)]))
        finally:
            fleet.stop()


class TestRulesDrivenAutoscaler:
    def test_firing_alert_is_pressure_for_its_model(self, tmp_path,
                                                    net_v1):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.alerts import (AlertEngine,
                                                       AlertRule)
        from deeplearning4j_tpu.monitor.flightrec import FlightRecorder
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        mreg = monitor.enable(registry=MetricsRegistry())
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            fleet.publish_gauges()
            rules = AlertEngine(
                mreg,
                [AlertRule(name="lm-hot", kind="threshold",
                           metric="fleet_model_version",
                           labels={"model": "lm"}, op=">=", value=1.0,
                           severity="page")],
                recorder=FlightRecorder(), registry=MetricsRegistry())
            scaler = FleetAutoscaler(fleet, rules=rules, factor=2,
                                     max_slots=2, max_blocks=16)
            made = scaler.check()
            assert len(made) == 1
            assert "alert lm-hot firing" in made[0]["reason"]
            assert fleet.server("lm").engine.n_slots == 2
            # at the cap: a still-firing alert cannot scale further
            fleet.publish_gauges()
            assert scaler.check() == []
        finally:
            fleet.stop()
            monitor.disable()

    def test_quiet_rules_never_scale(self, tmp_path, net_v1):
        from deeplearning4j_tpu.monitor.alerts import AlertEngine
        from deeplearning4j_tpu.monitor.flightrec import FlightRecorder
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            rules = AlertEngine(lambda: {}, [],
                                recorder=FlightRecorder())
            scaler = FleetAutoscaler(fleet, rules=rules,
                                     queue_depth_high=0)
            # legacy thresholds would see pressure at depth 0 — rules
            # mode must consult the (empty, quiet) rule set instead
            assert scaler.check() == []
            assert fleet.server("lm").engine.n_slots == 1
        finally:
            fleet.stop()

    def test_goodput_floor_reads_live_ledger(self, tmp_path, net_v1,
                                             prompts):
        """`goodput_low=` pressure through the LIVE fallback (monitoring
        off): a warmed server whose run is warmup-dominated sits far
        below the floor once real traffic lands."""
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.alerts import AlertEngine
        from deeplearning4j_tpu.monitor.flightrec import FlightRecorder
        assert not monitor.is_enabled()
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        quiet = AlertEngine(lambda: {}, [], recorder=FlightRecorder())
        scaler = FleetAutoscaler(fleet, rules=quiet, goodput_low=0.99,
                                 factor=2, max_slots=2, max_blocks=16)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL,
                         warmup_prompt_len=3)
            # warmed but idle: 0.0 fraction is absence of traffic, NOT
            # waste — the floor must not fire yet
            assert scaler.check() == []
            router.submit("lm", prompts[0], 6).result(timeout=120)
            srv = fleet.server("lm")
            assert 0.0 < srv.engine.goodput.goodput_fraction() < 0.99
            made = scaler.check()
            assert len(made) == 1
            assert "goodput fraction" in made[0]["reason"]
            assert fleet.server("lm").engine.n_slots == 2
        finally:
            fleet.stop()


# ======================================================= UI + bench gate
class TestFleetObservability:
    def test_serving_page_per_model_rows_and_metrics(self, tmp_path,
                                                     net_v1, net_v2,
                                                     prompts):
        import urllib.request

        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        from deeplearning4j_tpu.ui import UIServer

        mreg = monitor.enable(registry=MetricsRegistry())
        reg = ModelRegistry(tmp_path)
        reg.publish("alpha", net_v1)
        reg.publish("beta", net_v2)
        fleet = FleetServer(reg)
        router = FleetRouter(fleet)
        ui = UIServer(registry=mreg).start()
        try:
            fleet.deploy("alpha", n_slots=1, n_blocks=8, block_len=BL)
            fleet.deploy("beta", n_slots=2, n_blocks=8, block_len=BL)
            router.submit("alpha", prompts[0], 4).result(timeout=120)
            fleet.publish_gauges()
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(base + "/serving",
                                          timeout=10).read().decode()
            # per-model rows: name, version, queue depth, active
            # slots, shed — the fleet table
            for frag in ("fleet", "alpha", "beta", "version",
                         "queue depth", "active slots", "shed"):
                assert frag in html, f"{frag!r} missing from /serving"
            mtext = urllib.request.urlopen(base + "/metrics",
                                           timeout=10).read().decode()
            for fam in ("fleet_active_models", "fleet_queue_depth",
                        "fleet_model_version", "fleet_streams_total",
                        "registry_published_total"):
                assert fam in mtext, f"{fam} missing from /metrics"
            assert 'model="alpha"' in mtext
            # undeploying zeroes a model's gauges (version=0 marks the
            # row retired) and the page drops it — no stale
            # live-looking rows for retired models
            fleet.undeploy("beta")
            html = urllib.request.urlopen(base + "/serving",
                                          timeout=10).read().decode()
            assert "alpha" in html
            assert "<td>beta</td>" not in html
        finally:
            fleet.stop()
            monitor.disable()
            ui.stop()

    def test_compare_bench_gates_fleet_metrics(self):
        from deeplearning4j_tpu.bench import compare_bench

        def rec(sustained, swap_p99, tps=20000.0):
            return {"platform": "cpu-sandbox", "value": 100.0,
                    "extras": {"serving_fleet": {
                        "streams_sustained": sustained,
                        "swap_p99_ttft_ms": swap_p99,
                        "tokens_per_sec": tps}}}

        base = rec(10240, 250.0)
        assert compare_bench(rec(10200, 260.0), base)["status"] == "pass"
        # a concurrency collapse gates (structural 5% band)
        v = compare_bench(rec(6000, 250.0), base)
        assert v["status"] == "regression"
        assert any(r["metric"] == "fleet_streams_sustained"
                   for r in v["regressions"])
        # swap-window TTFT is lower-is-better: a compile-cliff RISE
        # gates, a drop passes
        v = compare_bench(rec(10240, 2500.0), base)
        assert v["status"] == "regression"
        assert any(r["metric"] == "fleet_swap_p99_ttft_ms"
                   for r in v["regressions"])
        assert compare_bench(rec(10240, 50.0), base)["status"] == "pass"


# ==================================================== wire trace context
class TestWireTracePropagation:
    """Satellite: a client-minted trace id crosses the ND4T wire and the
    router-side server spans stitch onto the SAME timeline (one track)
    as the client's wire-level trace."""

    @pytest.fixture
    def mon(self):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import MetricsRegistry, Tracer
        reg, tr = MetricsRegistry(), Tracer()
        monitor.enable(registry=reg, tracer=tr)
        yield reg, tr
        monitor.disable()
        monitor._STATE.registry = monitor.GLOBAL_REGISTRY
        monitor._STATE.tracer = monitor.GLOBAL_TRACER

    @staticmethod
    def _req_events(tracer, trace_id):
        return [e for e in tracer.events()
                if str(e.get("name", "")).startswith("req/")
                and e.get("args", {}).get("trace_id") == trace_id]

    def test_remote_stream_stitches_one_timeline(self, mon, tmp_path,
                                                 net_v1, prompts,
                                                 ref_v1):
        from deeplearning4j_tpu.monitor.reqtrace import _tid_for
        from deeplearning4j_tpu.streaming import LocalQueueTransport
        _, tracer = mon
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        tr = LocalQueueTransport()
        router = FleetRouter(fleet, transport=tr)
        try:
            fleet.deploy("lm", n_slots=2, n_blocks=16, block_len=BL)
            router.serve()
            client = FleetClient(tr)
            remote = client.generate("lm", prompts[0], 6)
            got = remote.result(timeout=120)
            np.testing.assert_array_equal(got, ref_v1[0])
            tid = remote.trace_id
            assert tid is not None
            assert remote.trace is not None and remote.trace.finished
            # the server-side trace flushes when the scheduler finishes
            # the stream, a hair after the done-reply reaches us
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                evs = self._req_events(tracer, tid)
                if sum(e["name"] == "req/lifetime" for e in evs) >= 2:
                    break
                time.sleep(0.02)
            evs = self._req_events(tracer, tid)
            names = {e["name"] for e in evs}
            # client half (wire-level) + server half (scheduler phases)
            # of ONE trace id, all on one derived track
            assert {"req/wire_submit", "req/remote_stream",
                    "req/queued", "req/prefill", "req/decode",
                    "req/lifetime"} <= names
            assert sum(e["name"] == "req/lifetime" for e in evs) == 2
            assert {e["tid"] for e in evs} == {_tid_for(tid)}

            # the server-side phase sequence matches the LOCAL path's
            local = fleet.server("lm").generate_async(prompts[1], 6)
            local.result(timeout=120)
            local_names = [p["name"] for p in local.trace.phases]
            remote_side = [e for e in evs
                           if e["name"] in ("req/queued", "req/prefill",
                                            "req/decode")]
            assert [e["name"].removeprefix("req/")
                    for e in remote_side[:2]] == local_names[:2] \
                == ["queued", "prefill"]
        finally:
            router.stop()
            fleet.stop()

    def test_remote_shed_trace_annotated(self, mon, tmp_path, net_v1,
                                         prompts):
        from deeplearning4j_tpu.streaming import LocalQueueTransport
        _, tracer = mon
        reg = ModelRegistry(tmp_path)
        reg.publish("lm", net_v1)
        fleet = FleetServer(reg)
        tr = LocalQueueTransport()
        router = FleetRouter(fleet, transport=tr, max_queue=0)
        try:
            fleet.deploy("lm", n_slots=1, n_blocks=8, block_len=BL)
            router.serve()
            remote = FleetClient(tr).generate("lm", prompts[0], 6)
            with pytest.raises(ShedError):
                remote.result(timeout=60)
            assert remote.trace is not None
            assert remote.trace.status == "shed"
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                evs = self._req_events(tracer, remote.trace_id)
                if any(e["name"] == "req/shed" for e in evs):
                    break
                time.sleep(0.02)
            shed = [e for e in evs if e["name"] == "req/shed"]
            assert shed and shed[0]["args"]["reason"]
            assert shed[0]["args"].get("router") is True
        finally:
            router.stop()
            fleet.stop()

    def test_wire_header_carries_trace_id(self):
        from deeplearning4j_tpu.serving import wire
        data = wire.encode_request("lm", "rid1", np.arange(3), 4,
                                   trace_id="abcd1234abcd1234")
        header, _ = wire.decode_request(data)
        assert header["trace_id"] == "abcd1234abcd1234"
        # absent by default — old routers keep decoding new clients
        data = wire.encode_request("lm", "rid1", np.arange(3), 4)
        header, _ = wire.decode_request(data)
        assert "trace_id" not in header
