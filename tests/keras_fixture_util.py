"""Build Keras-dialect .h5 fixtures with h5py — independent of the
framework's own C++ HDF5 shim, so import tests exercise a real
third-party-written file (the reference vendors actual Keras files:
`deeplearning4j-modelimport/src/test/resources/configs/`).

Layouts reproduced byte-for-byte from real Keras output:
- Keras 2: root attr `model_config` (JSON); `/model_weights` group with
  `layer_names` attr; per-layer group attrs `weight_names` =
  [b"{lname}/kernel:0", ...]; datasets at
  `/model_weights/{lname}/{lname}/kernel:0`.
- Keras 1: weights at root `/{lname}` groups, weight names
  `{lname}_W` style (no nested scope, no ":0" suffix).
"""

import json

import h5py
import numpy as np


def write_keras2_h5(path, model_config: dict, layer_weights):
    """layer_weights: list of (layer_name, [(weight_name, array), ...]).
    weight_name is the short Keras name ("kernel", "bias", ...)."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        f.attrs["keras_version"] = b"2.2.4"
        f.attrs["backend"] = b"tensorflow"
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [ln.encode() for ln, _ in layer_weights], dtype="S64")
        mw.attrs["keras_version"] = b"2.2.4"
        mw.attrs["backend"] = b"tensorflow"
        for lname, weights in layer_weights:
            g = mw.create_group(lname)
            wnames = [f"{lname}/{wn}:0" for wn, _ in weights]
            g.attrs["weight_names"] = np.array(
                [w.encode() for w in wnames], dtype="S128")
            for (wn, arr), full in zip(weights, wnames):
                g.create_dataset(full, data=np.asarray(arr, np.float32))


def write_keras1_h5(path, model_config: dict, layer_weights):
    """Keras 1 dialect: weights at root, `{lname}_W`-style names."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        f.attrs["keras_version"] = b"1.2.2"
        f.attrs["layer_names"] = np.array(
            [ln.encode() for ln, _ in layer_weights], dtype="S64")
        for lname, weights in layer_weights:
            g = f.create_group(lname)
            wnames = [f"{lname}_{wn}" for wn, _ in weights]
            g.attrs["weight_names"] = np.array(
                [w.encode() for w in wnames], dtype="S128")
            for (wn, arr), full in zip(weights, wnames):
                g.create_dataset(full, data=np.asarray(arr, np.float32))


# ------------------------------------------------- numpy reference math
def np_conv2d_same(x, k, b, stride=1):
    """NHWC conv, 'same' padding, odd kernels — pure numpy oracle."""
    kh, kw, cin, cout = k.shape
    ph, pw = kh // 2, kw // 2
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    B, H, W, _ = x.shape
    out = np.zeros((B, -(-H // stride), -(-W // stride), cout), np.float32)
    for i in range(out.shape[1]):
        for j in range(out.shape[2]):
            patch = xp[:, i * stride:i * stride + kh, j * stride:j * stride + kw, :]
            out[:, i, j, :] = np.tensordot(patch, k, axes=([1, 2, 3], [0, 1, 2]))
    return out + b


def np_maxpool2d(x, size=2):
    B, H, W, C = x.shape
    h, w = H // size, W // size
    return x[:, :h * size, :w * size, :].reshape(
        B, h, size, w, size, C).max(axis=(2, 4))


def np_hard_sigmoid(x):
    return np.clip(0.2 * x + 0.5, 0.0, 1.0)


def np_lstm(x, K, R, b):
    """Keras-2 LSTM (IFCO kernels, hard_sigmoid gates, tanh): returns
    final hidden state [B, U]."""
    B, T, F = x.shape
    U = R.shape[0]
    h = np.zeros((B, U), np.float32)
    c = np.zeros((B, U), np.float32)
    for t in range(T):
        z = x[:, t, :] @ K + h @ R + b
        i = np_hard_sigmoid(z[:, :U])
        f = np_hard_sigmoid(z[:, U:2 * U])
        cc = np.tanh(z[:, 2 * U:3 * U])
        o = np_hard_sigmoid(z[:, 3 * U:])
        c = f * c + i * cc
        h = o * np.tanh(c)
    return h


def np_softmax(z):
    e = np.exp(z - z.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def np_separable_conv2d_valid(x, dk, pk, b):
    """Depthwise-separable conv, 'valid' padding, stride 1 — numpy oracle.
    dk [kh,kw,cin,dm], pk [1,1,cin*dm,cout]."""
    kh, kw, cin, dm = dk.shape
    B, H, W, _ = x.shape
    oh, ow = H - kh + 1, W - kw + 1
    mid = np.zeros((B, oh, ow, cin * dm), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + kh, j:j + kw, :]          # [B, kh, kw, cin]
            # depthwise: per input channel, dm outputs (in-major layout)
            prod = np.einsum("bhwc,hwcd->bcd", patch, dk)  # [B, cin, dm]
            mid[:, i, j, :] = prod.reshape(B, cin * dm)
    out = mid @ pk[0, 0]                                  # [B, oh, ow, cout]
    return out + b
