"""Property-style fuzz: RANDOM small models written by the real keras
package must import with output parity. Complements the fixed golden
fixtures (`test_keras_real_golden.py`) by covering layer COMBINATIONS
none of the hand-picked fixtures hit — each seed builds a different
stack of conv/pool/norm/dense/recurrent layers.

Needs the keras pip package (skipped where absent). Seeds beyond the
default three: DL4J_KERAS_FUZZ_SEEDS=n.
"""

import os

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from keras import layers  # noqa: E402

from deeplearning4j_tpu.modelimport.keras import KerasModelImport  # noqa: E402

N_SEEDS = int(os.environ.get("DL4J_KERAS_FUZZ_SEEDS", "3"))


def _random_cnn(rng):
    """Random conv stack: conv/pool/bn blocks ending in dense softmax."""
    mdl = [keras.Input(shape=(12, 12, 2))]
    n_blocks = rng.integers(1, 3)
    for b in range(n_blocks):
        filters = int(rng.choice([3, 4, 6]))
        ksz = int(rng.choice([1, 3]))
        pad = str(rng.choice(["same", "valid"]))
        mdl.append(layers.Conv2D(filters, ksz, padding=pad,
                                 activation=str(rng.choice(
                                     ["relu", "tanh", "linear"])),
                                 use_bias=bool(rng.integers(0, 2)),
                                 name=f"conv{b}"))
        if rng.integers(0, 2):
            mdl.append(layers.BatchNormalization(name=f"bn{b}"))
        if rng.integers(0, 2):
            pool = (layers.MaxPooling2D if rng.integers(0, 2)
                    else layers.AveragePooling2D)
            mdl.append(pool(2, name=f"pool{b}"))
    mdl.append(layers.Flatten(name="flatten"))
    if rng.integers(0, 2):
        mdl.append(layers.Dense(int(rng.choice([5, 8])), activation="relu",
                                name="hidden"))
    mdl.append(layers.Dense(3, activation="softmax", name="out"))
    x = rng.standard_normal((2, 12, 12, 2)).astype(np.float32)
    return keras.Sequential(mdl, name="fuzz_cnn"), x


def _random_rnn(rng):
    T, F = int(rng.choice([3, 5])), int(rng.choice([2, 4]))
    mdl = [keras.Input(shape=(T, F))]
    cls = layers.LSTM if rng.integers(0, 2) else layers.SimpleRNN
    units = int(rng.choice([4, 6]))
    return_seq = bool(rng.integers(0, 2))
    mdl.append(cls(units, return_sequences=return_seq, name="rnn"))
    if return_seq:
        mdl.append(layers.LSTM(3, name="rnn2"))
    mdl.append(layers.Dense(2, activation="softmax", name="out"))
    x = rng.standard_normal((2, T, F)).astype(np.float32)
    return keras.Sequential(mdl, name="fuzz_rnn"), x


@pytest.mark.parametrize("seed", range(N_SEEDS))
@pytest.mark.parametrize("family", ["cnn", "rnn"])
def test_random_keras_model_round_trips(tmp_path, seed, family):
    salt = 1000 * seed + (0 if family == "cnn" else 1)
    rng = np.random.default_rng(salt)
    # seed Keras's global RNG too — otherwise layer WEIGHTS differ on
    # re-run and a near-tolerance failure becomes an unreproducible flake
    keras.utils.set_random_seed(salt)
    model, x = (_random_cnn if family == "cnn" else _random_rnn)(rng)
    want = model.predict(x, verbose=0)
    path = tmp_path / f"fuzz_{family}_{seed}.h5"
    model.save(path)
    net = KerasModelImport.import_keras_model_and_weights(str(path))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5,
                               err_msg=f"seed={seed} family={family} "
                                       f"layers={[l.name for l in model.layers]}")
