"""Elastic multi-process training runtime (parallel/elastic.py +
multihost lifecycle).

Acceptance surface: the membership coordinator commits rank-ordered,
port-bumped generations from register/heartbeat/leave/eviction events;
control-plane I/O retries with bounded backoff and degrades to the last
known topology; the `initialize_multihost` latch is re-armable through
`shutdown_multihost` (re-init with a DIFFERENT topology is well-defined);
an in-process `ElasticTrainer` survives a mid-run join + leave (two
reconfigurations, mesh re-formed each time) with loss parity against an
uninterrupted run; `reshard_replica_stack` holds its conservation
contracts through shrink-to-1 / non-divisible / 4→2→4 sequences; and an
all-corrupt checkpoint directory names every candidate tried. The real
4-process SIGKILL shrink/grow drill lives in scripts/fault_drill.py
--elastic-smoke (scripts/verify.sh).
"""

import json
import shutil
import tempfile
import threading
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu import fault, monitor
from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.fault import state as fstate
from deeplearning4j_tpu.fault.errors import (
    ElasticMembershipError,
    ElasticReconfiguration,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import (
    ElasticClient,
    ElasticConfig,
    ElasticCoordinator,
    ElasticTrainer,
    distributed_failure,
    retry_request,
)


@pytest.fixture
def tmpdir_():
    d = tempfile.mkdtemp(prefix="elastic_test_")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def coordinator():
    co = ElasticCoordinator(settle_s=0.05, grace_s=0.6, tick_s=0.01,
                            min_members=1).start()
    yield co
    co.stop()


def wait_for(pred, timeout=10.0, poll=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise AssertionError(f"{what} not reached within {timeout}s")


# ================================================= coordinator + client
class TestCoordinator:
    def test_register_commits_rank_ordered_generation(self, coordinator):
        a = ElasticClient(coordinator.address, "bb")
        b = ElasticClient(coordinator.address, "aa")
        a.register(host="hostA", device_count=2)
        b.register(host="hostB", device_count=1)
        plan = a.await_member_plan(timeout_s=10)
        assert plan["num_processes"] == 2
        # rank order is token order — deterministic across processes
        assert [m["token"] for m in plan["members"]] == ["aa", "bb"]
        assert b.my_rank(plan) == 0 and a.my_rank(plan) == 1
        # jax coordinator lands on rank 0's host at a generation-bumped
        # port
        gen = plan["generation"]
        base = coordinator.jax_port_base
        assert plan["coordinator_address"] == \
            f"hostB:{base + (gen % coordinator.jax_port_span)}"

    def test_join_wave_coalesces_into_one_generation(self, coordinator):
        clients = [ElasticClient(coordinator.address, f"w{i}")
                   for i in range(4)]
        for c in clients:
            c.register()
        plan = clients[0].await_member_plan(timeout_s=10)
        assert plan["num_processes"] == 4
        # the simultaneous wave must not have burned one generation per
        # member (settle window coalesces)
        assert plan["generation"] <= 2

    def test_missed_heartbeats_evict_and_bump_generation(self,
                                                         coordinator):
        stay = ElasticClient(coordinator.address, "stay",
                             heartbeat_interval_s=0.05)
        ghost = ElasticClient(coordinator.address, "ghost")
        stay.register()
        ghost.register()
        stay.start_heartbeats()
        plan = stay.await_member_plan(timeout_s=10)
        assert plan["num_processes"] == 2
        # ghost never heartbeats -> evicted after grace -> new
        # generation without it
        plan = wait_for(
            lambda: (stay.current_plan()
                     if stay.current_plan()["num_processes"] == 1
                     else None),
            what="eviction generation")
        assert [m["token"] for m in plan["members"]] == ["stay"]
        stay.stop()

    def test_leave_and_port_bump_across_generations(self, coordinator):
        a = ElasticClient(coordinator.address, "a",
                          heartbeat_interval_s=0.05)
        b = ElasticClient(coordinator.address, "b")
        a.register(), b.register()
        a.start_heartbeats()
        p1 = a.await_member_plan(timeout_s=10)
        b.leave("shrink")

        def post_leave():
            plan = a.await_member_plan(timeout_s=1)
            return plan if plan["num_processes"] == 1 else None
        p2 = wait_for(post_leave, what="post-leave plan")
        a.stop()
        assert p2["generation"] > p1["generation"]
        # a half-dead predecessor jax service can't poison the new world
        assert p2["coordinator_address"] != p1["coordinator_address"]

    def test_status_reports_member_info(self, coordinator):
        c = ElasticClient(coordinator.address, "w0",
                          heartbeat_interval_s=0.05)
        c.register(device_count=4)
        c.start_heartbeats()
        c.set_info(step=17, phase="fit")
        st = wait_for(
            lambda: (c.status()
                     if c.status()["members"].get("w0", {}).get(
                         "info", {}).get("step") == 17 else None),
            what="heartbeat info propagation")
        assert st["members"]["w0"]["device_count"] == 4
        c.stop()

    def test_metrics_surface(self, coordinator):
        reg = monitor.MetricsRegistry()
        monitor.enable(registry=reg)
        try:
            c = ElasticClient(coordinator.address, "w0")
            c.register()
            c.await_member_plan(timeout_s=10)
            snap = reg.snapshot()
            assert "elastic_live_processes" in snap
            assert "elastic_generation" in snap
        finally:
            monitor.disable()


class TestControlPlaneRetry:
    def test_unreachable_raises_typed_error_after_attempts(self):
        t0 = time.monotonic()
        with pytest.raises(ElasticMembershipError, match="unreachable"):
            retry_request("127.0.0.1:1", {"op": "status"}, timeout=0.2,
                          attempts=3, backoff_s=0.05)
        # 3 attempts with 0.05 * 2**k backoff: two sleeps happened
        assert time.monotonic() - t0 >= 0.05 + 0.10

    def test_rejected_op_does_not_retry(self, coordinator):
        with pytest.raises(ElasticMembershipError, match="rejected"):
            retry_request(coordinator.address, {"op": "no-such-op"})

    def test_heartbeat_survives_control_plane_outage(self, coordinator):
        c = ElasticClient(coordinator.address, "w0",
                          heartbeat_interval_s=0.05, io_timeout_s=0.2,
                          backoff_s=0.01)
        c.register()
        c.start_heartbeats()
        c.await_member_plan(timeout_s=10)
        # kill the control plane mid-heartbeats: the client must degrade
        # to a warning (training continues), not raise on its thread
        coordinator.stop()
        time.sleep(0.3)
        assert c._thread.is_alive()
        assert c.generation() >= 1   # last known topology retained
        c.stop()

    def test_evicted_client_reregisters(self, coordinator):
        c = ElasticClient(coordinator.address, "w0",
                          heartbeat_interval_s=0.05)
        c.register()
        c.await_member_plan(timeout_s=10)
        # simulate a long GIL stall: evict server-side, then let the
        # heartbeat thread discover it and re-register
        with coordinator._lock:
            coordinator._members.pop("w0", None)
            coordinator._dirty_since = time.monotonic()
        c.start_heartbeats()
        wait_for(lambda: "w0" in coordinator.status()["members"],
                 what="re-registration")
        c.stop()

    def test_distributed_failure_classifier(self):
        assert distributed_failure(RuntimeError(
            "DEADLINE_EXCEEDED: heartbeat timeout"))
        assert distributed_failure(OSError("Connection reset by peer"))
        assert not distributed_failure(ValueError("bad batch size"))


# ================================================ multihost latch lifecycle
class TestMultihostLatch:
    @pytest.fixture(autouse=True)
    def _stub_collectives(self, monkeypatch):
        # the real gloo selection poisons later single-process CPU
        # backend creation in this test process (gloo needs a
        # distributed client) — these tests exercise the LATCH, not
        # the collectives
        from deeplearning4j_tpu.parallel import multihost as mh
        monkeypatch.setattr(mh, "_enable_cpu_collectives", lambda: None)

    def test_shutdown_rearms_initialize(self, monkeypatch):
        from deeplearning4j_tpu.parallel import multihost as mh
        calls = []
        monkeypatch.setattr(
            mh, "_raw_initialize",
            lambda addr, n, pid, **kw: calls.append((addr, n, pid)))
        monkeypatch.setattr(mh, "_clear_topology_caches", lambda: None)
        monkeypatch.setattr(mh.jax.distributed, "shutdown", lambda: None)
        monkeypatch.setattr(mh.initialize_multihost, "_done", False,
                            raising=False)

        mh.initialize_multihost("127.0.0.1:9990", 2, 0)
        assert mh.multihost_active()
        mh.initialize_multihost("127.0.0.1:9990", 2, 0)   # idempotent
        assert calls == [("127.0.0.1:9990", 2, 0)]

        mh.shutdown_multihost()
        assert not mh.multihost_active()
        mh.shutdown_multihost()                           # no-op when down

        # re-initialization with a DIFFERENT topology is well-defined
        mh.initialize_multihost("127.0.0.1:9991", 3, 1)
        assert calls[-1] == ("127.0.0.1:9991", 3, 1)
        assert mh.multihost_active()
        mh.shutdown_multihost()

    def test_initialize_retries_transient_then_succeeds(self,
                                                        monkeypatch):
        from deeplearning4j_tpu.parallel import multihost as mh
        attempts = []

        def flaky(addr, n, pid, **kw):
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("DEADLINE_EXCEEDED: coordinator "
                                   "not reachable")

        monkeypatch.setattr(mh, "_raw_initialize", flaky)
        monkeypatch.setattr(mh, "_reset_distributed_state", lambda: None)
        monkeypatch.setattr(mh.initialize_multihost, "_done", False,
                            raising=False)
        mh.initialize_multihost("127.0.0.1:9992", 2, 0, max_attempts=4,
                                backoff_s=0.01)
        assert len(attempts) == 3 and mh.multihost_active()
        monkeypatch.setattr(mh, "_clear_topology_caches", lambda: None)
        monkeypatch.setattr(mh.jax.distributed, "shutdown", lambda: None)
        mh.shutdown_multihost()

    def test_initialize_nontransient_raises_immediately(self,
                                                        monkeypatch):
        from deeplearning4j_tpu.parallel import multihost as mh
        attempts = []

        def broken(addr, n, pid, **kw):
            attempts.append(1)
            raise RuntimeError("invalid process id")

        monkeypatch.setattr(mh, "_raw_initialize", broken)
        monkeypatch.setattr(mh, "_reset_distributed_state", lambda: None)
        monkeypatch.setattr(mh.initialize_multihost, "_done", False,
                            raising=False)
        with pytest.raises(RuntimeError, match="invalid process id"):
            mh.initialize_multihost("127.0.0.1:9993", 2, 0,
                                    max_attempts=4, backoff_s=0.01)
        assert len(attempts) == 1
        assert not mh.multihost_active()


# ======================================================= reshard edges
class TestReshardEdges:
    def test_shrink_to_one_replica(self):
        tree = {"0": {"W": np.arange(24, dtype=np.float32).reshape(4, 6)}}
        res = fstate.reshard_replica_stack(tree, 1, kind="residual")
        assert res["0"]["W"].shape == (1, 6)
        assert np.allclose(res["0"]["W"][0],
                           tree["0"]["W"].sum(axis=0))
        st = fstate.reshard_replica_stack(tree, 1, kind="state")
        assert np.allclose(st["0"]["W"][0], tree["0"]["W"].mean(axis=0))

    def test_grow_non_divisible(self):
        # 3 -> 4 and 4 -> 6: no divisibility assumption anywhere
        tree = {"0": {"W": np.arange(12, dtype=np.float32).reshape(3, 4)}}
        res = fstate.reshard_replica_stack(tree, 4, kind="residual")
        assert res["0"]["W"].shape == (4, 4)
        assert np.isclose(res["0"]["W"].sum(dtype=np.float64),
                          tree["0"]["W"].sum(dtype=np.float64))
        t4 = {"0": {"W": np.arange(8, dtype=np.float32).reshape(4, 2)}}
        res6 = fstate.reshard_replica_stack(t4, 6, kind="residual")
        assert res6["0"]["W"].shape == (6, 2)
        assert np.isclose(res6["0"]["W"].sum(dtype=np.float64),
                          t4["0"]["W"].sum(dtype=np.float64))

    def test_sequence_4_2_4_conserves_mass(self):
        rng = np.random.default_rng(3)
        tree = {"0": {"W": rng.standard_normal((4, 5)).astype(np.float32)}}
        through = fstate.reshard_replica_stack(
            fstate.reshard_replica_stack(tree, 2, kind="residual"),
            4, kind="residual")
        assert np.isclose(
            through["0"]["W"].sum(dtype=np.float64),
            tree["0"]["W"].sum(dtype=np.float64), rtol=1e-6)

    def test_threshold_rs_4_2_4_checkpoint_roundtrip(self, tmpdir_):
        """ZeRO-mode elastic round-trip: train 4-wide, resume 2-wide,
        resume 4-wide — the sharded updater state re-slices from the
        full-tree checkpoint at every width and training proceeds."""
        from deeplearning4j_tpu.parallel.tensor import fsdp_param_specs
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        rng = np.random.default_rng(0)
        x = rng.standard_normal((48, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]

        def build():
            conf = (NeuralNetConfiguration.builder().seed(7)
                    .updater(Adam(0.01)).list()
                    .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
                    .layer(OutputLayer(n_in=16, n_out=3,
                                       activation="softmax", loss="mcxent"))
                    .set_input_type(InputType.feed_forward(8)).build())
            return MultiLayerNetwork(conf)

        def run_width(n, epochs_total):
            mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
            net = build().init()   # param shapes feed fsdp_param_specs
            it = ArrayDataSetIterator(x, y, batch_size=8, shuffle=True,
                                      seed=11)
            tr = ParallelTrainer(
                net, mesh, mode="sync", gradient_sharing="threshold_rs",
                rs_param_specs=fsdp_param_specs(net, axis_size=n,
                                                min_shard_elems=1))
            ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10)
            net.add_listener(fault.CheckpointListener(ck, frequency=2,
                                                      iterator=it))
            try:
                tr.resume(tmpdir_, iterator=it)
            except FileNotFoundError:
                pass
            start = net.iteration_count
            tr.fit(it, epochs=epochs_total - net.epoch_count, batch_size=8)
            ck.wait()
            return net, start

        n1, s1 = run_width(4, 1)
        assert s1 == 0 and n1.iteration_count == 6
        n2, s2 = run_width(2, 2)
        assert s2 == 6 and n2.iteration_count == 12
        n3, s3 = run_width(4, 3)
        # the fresh listener's cadence can land the newest checkpoint a
        # step or two before the fit end — mid-epoch resume is part of
        # the contract, the exact step is not
        assert 10 <= s3 <= 12 and n3.iteration_count == 18
        saved, _ = fault.load_latest_valid(tmpdir_)
        res = saved["arrays"]["trainer"]["residual_r"]
        assert fstate.stacked_replica_count(res) == 4

    def test_all_corrupt_names_every_candidate(self, tmpdir_):
        ck = fault.AsyncCheckpointer(tmpdir_, keep_last=10)
        for i in (3, 6, 9):
            ck.save({"arrays": {"params": {"0": {"W": np.ones(
                (2, 2), np.float32) * i}}},
                "meta": {"iteration_count": i, "epoch_count": 0}}, i)
            ck.wait()   # the busy-writer drop would skip middle steps
        for s in (3, 6, 9):
            fault.corrupt_checkpoint(tmpdir_, step=s, mode="flip")
        with pytest.raises(fault.CheckpointCorruptError) as ei:
            fault.load_latest_valid(tmpdir_)
        msg = str(ei.value)
        # the elastic-resume damage report names EVERY candidate tried
        assert "3 candidates tried" in msg
        for s in (3, 6, 9):
            assert f"step {s}" in msg


# ============================================= in-process elastic trainer
def _build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf)


def _make_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((240, 8)).astype(np.float32)
    w = rng.standard_normal((8, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


class _InProcessElasticTrainer(ElasticTrainer):
    """Elastic trainer with the jax.distributed seams stubbed: the
    membership/generation/drain/checkpoint/re-shard machinery runs for
    real, the mesh follows the plan's member count over LOCAL devices
    (1 member -> 4 devices, 2 members -> 2 devices: a shrink in
    disguise, exercising the re-shard path without OS processes)."""

    def _init_runtime(self, plan):
        pass

    def _teardown_runtime(self):
        pass

    def _mesh(self, plan):
        n = 4 if plan["num_processes"] == 1 else 2
        return Mesh(np.array(jax.devices()[:n]), ("data",))


class TestElasticTrainerInProcess:
    @pytest.mark.parametrize("gradient_sharing", [None, "threshold"])
    def test_survives_join_and_leave(self, tmpdir_, gradient_sharing):
        x, y = _make_data()

        def make_iter():
            return ArrayDataSetIterator(x, y, batch_size=24, shuffle=True,
                                        seed=11)

        # uninterrupted reference on the 4-device mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        ref = _build_net().init()
        ref_losses = {}

        class RefCollect:
            def iteration_done(self, model, iteration, epoch, score,
                               **info):
                ref_losses[iteration] = float(score)
        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        class RefL(TrainingListener):
            iteration_done = RefCollect().iteration_done
        ref.add_listener(RefL())
        ParallelTrainer(ref, Mesh(np.array(jax.devices()[:4]), ("data",)),
                        mode="sync",
                        gradient_sharing=gradient_sharing).fit(
            make_iter(), epochs=3, batch_size=24)

        co = ElasticCoordinator(settle_s=0.1, grace_s=1.5, tick_s=0.02,
                                min_members=1).start()
        try:
            cfg = ElasticConfig(control_address=co.address, token="w0",
                                heartbeat_interval_s=0.05)
            et = _InProcessElasticTrainer(
                _build_net, config=cfg, ckpt_dir=tmpdir_,
                ckpt_frequency=4, gradient_sharing=gradient_sharing)
            losses = {}

            class L(TrainingListener):
                def iteration_done(self, model, iteration, epoch, score,
                                   **info):
                    losses[iteration] = float(score)
                    # pace the fit against the control plane: generation
                    # bumps travel heartbeat (0.05s) -> settle (0.1s) ->
                    # next step boundary; an unthrottled in-process run
                    # can finish all 30 steps before the leave-triggered
                    # generation ever reaches the drain listener
                    time.sleep(0.05)

            # a fake member joins once w0 is under way and leaves later:
            # two reconfigurations, each with drain + checkpoint +
            # mesh re-form + resume
            def fake_member():
                c = ElasticClient(co.address, "zz-fake",
                                  heartbeat_interval_s=0.05)

                def fleet_step(k):
                    def check():
                        st = c.status()
                        steps = [m["info"].get("step", 0)
                                 for m in st["members"].values()]
                        return steps and max(steps) >= k
                    return check
                wait_for(fleet_step(8), timeout=300, what="step 8")
                c.register()
                c.start_heartbeats()
                wait_for(fleet_step(20), timeout=300, what="step 20")
                c.stop()
                c.leave("shrink")

            th = threading.Thread(target=fake_member, daemon=True)
            th.start()
            model = et.fit(make_iter, epochs=3, batch_size=24,
                           extra_listeners=lambda gen: [L()])
            th.join(timeout=10)
        finally:
            co.stop()

        assert model.iteration_count == ref.iteration_count
        gens = [h["generation"] for h in et.history]
        assert len(gens) >= 3, gens          # initial + join + leave
        # resumes actually restored state (not cold restarts)
        assert all(h["resumed"] for h in et.history[1:]), et.history
        if gradient_sharing == "threshold":
            assert any(h["residual_restored"] for h in et.history[1:])
        # dense sync is deterministic across the same device set: the
        # re-formed runs must track the uninterrupted reference. The
        # threshold path re-shards residual across 4->2->4 replicas and
        # the shrunk segment runs different replica math entirely, so it
        # holds the drill's drift band (fraction of the initial loss)
        init_loss = ref_losses[0]
        for i, r in ref_losses.items():
            assert i in losses, f"no loss recorded for step {i}"
            band = (5e-3 * max(1.0, abs(r)) if gradient_sharing is None
                    else 0.25 * init_loss)
            assert abs(losses[i] - r) <= band, (i, losses[i], r)
        pa = np.concatenate([np.ravel(np.asarray(l)) for l in
                             jax.tree_util.tree_leaves(model.params)])
        pb = np.concatenate([np.ravel(np.asarray(l)) for l in
                             jax.tree_util.tree_leaves(ref.params)])
        atol = 2e-3 if gradient_sharing is None else 0.15
        np.testing.assert_allclose(pa, pb, atol=atol)

    def test_drain_raises_elastic_reconfiguration(self, tmpdir_):
        """Unit seam: the drain listener's agreement + typed signal."""
        from deeplearning4j_tpu.parallel.elastic import (
            _DrainListener,
            make_drain_check,
        )
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        check = make_drain_check(mesh)
        assert check(False) is False
        assert check(True) is True

        co = ElasticCoordinator(settle_s=0.02, grace_s=5, tick_s=0.01,
                                min_members=1).start()
        try:
            c = ElasticClient(co.address, "w0")
            c.register()
            c.await_member_plan(timeout_s=10)
            run_gen = c.generation()
            lst = _DrainListener(c, run_gen, check)
            model = _build_net().init()
            # same generation: no drain
            lst.iteration_done(model, 0, 0, 1.0)
            # stale generation: drains with the typed signal
            other = ElasticClient(co.address, "w1")
            other.register()

            def bumped():
                # no heartbeat thread on c: poll + absorb explicitly
                c._absorb(c._request({"op": "plan"}))
                return c.generation() != run_gen or None
            wait_for(bumped, what="generation bump")
            with pytest.raises(ElasticReconfiguration) as ei:
                lst.iteration_done(model, 5, 0, 1.0)
            assert ei.value.step == 6
            assert ei.value.generation > run_gen
        finally:
            co.stop()
