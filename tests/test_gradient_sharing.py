"""Threshold-encoded gradient sharing (parallel/gradient_sharing.py):
encode/decode/error-feedback units, adaptive-τ controller, mode
resolution + conf serde, convergence parity vs dense sync training
(deep MLP with packed ``stacked::`` runs, TransformerLM with
scan_layers + fused multi-step, DP x TP), and the comm-bytes
accounting seam (benchtools/hlo_cost.collective_table /
comm_bytes_block)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresListener
from deeplearning4j_tpu.parallel import gradient_sharing as gs
from deeplearning4j_tpu.parallel.mesh import MeshSpec, device_mesh, make_mesh
from deeplearning4j_tpu.parallel.tensor import ShardedParallelTrainer
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer


def deep_mlp(n_hidden=6, seed=7, lr=0.01):
    """Deep homogeneous MLP — the hidden stack forms ONE scan run that
    packs at the train-step boundary (stacked:: entries)."""
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr)).list()
    for _ in range(n_hidden):
        b = b.layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
    conf = (b.layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def toy_data(n=320, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 16)).astype(np.float32)
    w = rng.standard_normal((16, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


# ---------------------------------------------------------------- unit level
class TestEncodeDecode:
    def test_error_feedback_identity(self):
        """enc*τ + residual == grad + old residual, exactly: nothing is
        ever lost to the compression."""
        rng = np.random.default_rng(3)
        acc = rng.standard_normal((64,)).astype(np.float32) * 0.01
        tau = jnp.float32(0.005)
        enc, res, sent = gs.encode_leaf(jnp.asarray(acc), tau, jnp.int8)
        rebuilt = (np.asarray(enc).astype(np.float32) * np.float32(0.005)
                   + np.asarray(res))
        np.testing.assert_allclose(rebuilt, acc, rtol=0, atol=1e-8)
        assert np.asarray(enc).dtype == np.int8
        assert set(np.unique(np.asarray(enc))) <= {-1, 0, 1}
        assert float(sent) == float(np.sum(np.abs(acc) >= 0.005))

    def test_wire_dtype(self):
        assert gs.wire_dtype(8) == jnp.int8
        assert gs.wire_dtype(127) == jnp.int8
        assert gs.wire_dtype(128) == jnp.int16
        with pytest.raises(ValueError, match="32767"):
            gs.wire_dtype(40000)

    def test_adapt_threshold_band(self):
        cfg = gs.ThresholdConfig()
        tau = jnp.float32(1e-3)
        # above the band: boost (send less)
        up = gs.adapt_threshold(tau, jnp.float32(0.5), cfg)
        assert float(up) == pytest.approx(1e-3 * cfg.boost)
        # below the band: decay (send more)
        down = gs.adapt_threshold(tau, jnp.float32(1e-5), cfg)
        assert float(down) == pytest.approx(1e-3 * cfg.decay)
        # inside: unchanged
        mid = gs.adapt_threshold(tau, jnp.float32(0.05), cfg)
        assert float(mid) == pytest.approx(1e-3)
        # clamp
        lo = gs.adapt_threshold(jnp.float32(1e-8), jnp.float32(0.0), cfg)
        assert float(lo) >= float(np.float32(cfg.min_threshold))

    def test_config_validation(self):
        with pytest.raises(ValueError, match="band"):
            gs.ThresholdConfig(sparsity_target_min=0.5,
                               sparsity_target_max=0.1)
        with pytest.raises(ValueError, match="decay"):
            gs.ThresholdConfig(decay=1.5)
        with pytest.raises(ValueError, match="min_threshold"):
            gs.ThresholdConfig(initial_threshold=2.0)


class TestModeResolution:
    def test_precedence(self, monkeypatch):
        conf = deep_mlp(2).conf
        assert gs.resolve_mode(None, conf) == "dense"
        conf.gradient_sharing = "threshold"
        assert gs.resolve_mode(None, conf) == "threshold"
        assert gs.resolve_mode("dense", conf) == "dense"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "threshold")
        assert gs.resolve_mode("dense", conf) == "threshold"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "0")
        assert gs.resolve_mode("threshold", conf) == "dense"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "bogus")
        with pytest.raises(ValueError, match="DL4J_GRADIENT_SHARING"):
            gs.resolve_mode(None, conf)

    def test_env_override_reaches_trainer(self, monkeypatch):
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "dense")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        assert t.gradient_sharing == "dense"

    def test_threshold_rejects_averaging_mode(self):
        with pytest.raises(ValueError, match="sync"):
            ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging",
                            gradient_sharing="threshold")

    def test_env_toggle_degrades_gracefully_for_averaging(self, monkeypatch):
        """The global DL4J_GRADIENT_SHARING=threshold A/B toggle must
        not crash unrelated averaging-mode trainers (it falls back to
        dense there); only an EXPLICIT arg/conf request hard-errors."""
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "threshold")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging")
        assert t.gradient_sharing == "dense"
        with pytest.raises(ValueError, match="sync"):
            ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging",
                            gradient_sharing="threshold")

    def test_mlc_serde_round_trip(self):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3))
                .gradient_sharing("threshold", threshold=5e-4)
                .build())
        assert conf.gradient_sharing == "threshold"
        assert conf.gradient_sharing_threshold == 5e-4
        back = type(conf).from_json(conf.to_json())
        assert back.gradient_sharing == "threshold"
        assert back.gradient_sharing_threshold == 5e-4
        # trainer picks the conf flag + τ0 up
        net = MultiLayerNetwork(back).init()
        t = ParallelTrainer(net, device_mesh(), mode="sync")
        assert t.gradient_sharing == "threshold"
        assert t.threshold_config.initial_threshold == 5e-4

    def test_graph_serde_round_trip(self):
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=3), "d")
                .set_outputs("out")
                .gradient_sharing("threshold", threshold=2e-3)
                .build())
        back = ComputationGraphConfiguration.from_json(conf.to_json())
        assert back.gradient_sharing == "threshold"
        assert back.gradient_sharing_threshold == 2e-3
        with pytest.raises(ValueError, match="dense|threshold"):
            (ComputationGraphConfiguration.graph_builder()
             .gradient_sharing("sparse"))


# --------------------------------------------------------- convergence parity
class TestConvergenceParity:
    def test_deep_mlp_threshold_tracks_dense(self):
        """Deep MLP (one packed stacked:: run), 50 sync steps: threshold
        with error feedback must learn and stay within tolerance of the
        dense trajectory; the per-replica residual must survive the
        pack/unpack boundary with per-LAYER keys."""
        x, y = toy_data()
        ds = DataSet(x, y)
        init = float(deep_mlp().score(ds))

        dense = deep_mlp()
        ParallelTrainer(dense, device_mesh(), mode="sync").fit(
            x, y, epochs=5, batch_size=32)
        thr = deep_mlp()
        t = ParallelTrainer(thr, device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        t.fit(x, y, epochs=5, batch_size=32)

        d, th = float(dense.score(ds)), float(thr.score(ds))
        assert d < 0.5 * init, f"dense failed to learn {init}->{d}"
        assert th < 0.5 * init, f"threshold failed to learn {init}->{th}"
        assert abs(th - d) <= 0.35 * init, (init, d, th)

        # residual: per-layer keys (stacked:: packing never leaks out),
        # per-replica leading axis, and nonzero — error feedback active
        res = t.threshold_residual()
        assert set(res.keys()) == set(thr.params.keys())
        assert not any(k.startswith("stacked::") for k in res)
        lead = res["0"]["W"].shape
        assert lead == (t.n_workers,) + thr.params["0"]["W"].shape
        assert any(float(np.abs(l).max()) > 0
                   for l in jax.tree_util.tree_leaves(res))
        # τ adapted away from its initial value — per-bucket tree on
        # the (default) bucketed path, per-layer keys like the residual
        assert isinstance(t._thr_tau, dict)
        assert set(t._thr_tau.keys()) == set(thr.params.keys())
        assert gs.tau_scalar(t._thr_tau) != pytest.approx(
            t.threshold_config.initial_threshold)

    def test_fused_multi_step_bit_identical(self):
        """steps_per_execution>1 (residual + τ riding the scan carry)
        must reproduce the per-step trajectory exactly — same numeric
        contract the dense fused path keeps."""
        x, y = toy_data(n=256, seed=1)

        def run(spe):
            net = deep_mlp(4)
            listener = CollectScoresListener()
            net.set_listeners(listener)
            t = ParallelTrainer(net, device_mesh(), mode="sync",
                                gradient_sharing="threshold")
            t.fit(x, y, epochs=3, batch_size=32, steps_per_execution=spe)
            return ([s for _, s in listener.scores],
                    {k: float(np.asarray(v))
                     for k, v in t._thr_tau.items()})

        per_step, tau1 = run(1)
        fused, tau4 = run(4)
        assert len(per_step) == len(fused) == 24
        np.testing.assert_allclose(per_step, fused, rtol=0, atol=0)
        assert tau1 == tau4

    def test_transformer_lm_threshold_tracks_dense(self):
        """TransformerLM with scan_layers on + fused multi-step: the
        threshold exchange must hold convergence parity through the
        scan-compiled, boundary-packed program."""
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        B, T, V = 16, 16, 37
        rng = np.random.default_rng(5)
        ids = rng.integers(0, V, (B * 4, T + 1))
        x = ids[:, :-1].astype(np.float32)
        y = np.eye(V, dtype=np.float32)[ids[:, 1:]]

        def build():
            lm = TransformerLM(vocab_size=V, d_model=32, n_layers=3,
                               n_heads=2, max_len=T)
            conf = lm.conf()
            assert conf.scan_layers
            net = MultiLayerNetwork(conf).init(11)
            return net

        def run(mode):
            net = build()
            listener = CollectScoresListener()
            net.set_listeners(listener)
            ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing=mode).fit(
                x, y, epochs=6, batch_size=B, steps_per_execution=4)
            return [s for _, s in listener.scores]

        dense = run("dense")
        thr = run("threshold")
        assert len(dense) == len(thr) == 24
        assert dense[-1] < dense[0]
        assert thr[-1] < thr[0], f"threshold LM failed to learn: {thr}"
        # parity band: same scale of progress from the same start
        assert abs(thr[-1] - dense[-1]) <= 0.35 * dense[0], (dense, thr)

    def test_sharded_dp_tp_threshold(self):
        """DP x TP (auto model axis): the compressed data-axis exchange
        composes with GSPMD tensor parallelism."""
        x, y = toy_data(n=256, seed=2)
        ds = DataSet(x, y)
        mesh = make_mesh(MeshSpec.of(data=4, model=2))
        init = float(deep_mlp(3).score(ds))

        thr = deep_mlp(3)
        t = ShardedParallelTrainer(thr, mesh, gradient_sharing="threshold")
        t.fit(x, y, epochs=6, batch_size=32)
        th = float(thr.score(ds))
        assert th < 0.6 * init, f"TP threshold failed to learn {init}->{th}"
        assert t._thr_residual_r is not None
        assert gs.tau_scalar(t._thr_tau) > 0


# ------------------------------------------------ bucketed (overlapped) exchange
def wide_mlp(seed=7, lr=0.01):
    """MLP wide enough that the default rs plan actually shards (the
    128-wide W leaves divide by the 8-way data axis and clear
    min_shard_elems) and deep enough to pack a stacked:: run."""
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr)).list()
    b = b.layer(DenseLayer(n_in=16, n_out=128, activation="tanh"))
    for _ in range(2):
        b = b.layer(DenseLayer(n_in=128, n_out=128, activation="tanh"))
    conf = (b.layer(OutputLayer(n_in=128, n_out=4, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def params_bitwise(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(la, lb))


class TestBucketedExchange:
    def test_bucketed_resolution(self, monkeypatch):
        """env > arg > default(True), mirroring DL4J_SCAN_LAYERS."""
        assert gs.resolve_bucketed() is True
        assert gs.resolve_bucketed(False) is False
        monkeypatch.setenv("DL4J_BUCKETED_EXCHANGE", "0")
        assert gs.resolve_bucketed(True) is False
        monkeypatch.setenv("DL4J_BUCKETED_EXCHANGE", "1")
        assert gs.resolve_bucketed(False) is True
        # a typo'd opt-out must raise, not silently stay bucketed
        monkeypatch.setenv("DL4J_BUCKETED_EXCHANGE", "flase")
        with pytest.raises(ValueError, match="DL4J_BUCKETED_EXCHANGE"):
            gs.resolve_bucketed()
        monkeypatch.delenv("DL4J_BUCKETED_EXCHANGE")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="sync")
        assert t.bucketed is True

    def test_dense_bucketed_tracks_single_barrier(self):
        """Bucketed dense (per-run pmean inside backward) vs the PR-4
        single-barrier GSPMD program: same math, different association
        — loss trajectories must agree within fp tolerance on a deep
        MLP whose hidden stack packs one stacked:: run."""
        x, y = toy_data(n=256, seed=4)

        def run(bucketed, scan):
            net = deep_mlp(4)
            net.conf.scan_layers = scan
            listener = CollectScoresListener()
            net.set_listeners(listener)
            ParallelTrainer(net, device_mesh(), mode="sync",
                            bucketed=bucketed).fit(
                x, y, epochs=3, batch_size=32)
            return np.asarray([s for _, s in listener.scores])

        for scan in (True, False):
            mono = run(False, scan)
            bkt = run(True, scan)
            assert len(mono) == len(bkt) == 24
            np.testing.assert_allclose(bkt, mono, rtol=0, atol=5e-5,
                                       err_msg=f"scan_layers={scan}")

    def test_threshold_bucketed_tracks_single_barrier(self):
        """Bucketed threshold (per-bucket residual/τ inside backward)
        vs the PR-4 single-barrier program: per-bucket τ adapts
        independently, so trajectories agree within the error-feedback
        band, and both learn."""
        x, y = toy_data(n=256, seed=5)
        ds = DataSet(x, y)
        init = float(deep_mlp().score(ds))

        def run(bucketed):
            net = deep_mlp()
            ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing="threshold",
                            bucketed=bucketed).fit(
                x, y, epochs=6, batch_size=32)
            return float(net.score(ds))

        mono, bkt = run(False), run(True)
        assert bkt < 0.6 * init, f"bucketed threshold failed: {init}->{bkt}"
        assert abs(bkt - mono) <= 0.35 * init, (init, mono, bkt)

    def test_transformer_bucketed_parity(self):
        """TransformerLM (scan_layers on and off): bucketed dense must
        track the single-barrier trajectory within fp tolerance through
        the scan-compiled, boundary-packed program, fused dispatch."""
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        B, T, V = 16, 16, 37
        rng = np.random.default_rng(6)
        ids = rng.integers(0, V, (B * 4, T + 1))
        x = ids[:, :-1].astype(np.float32)
        y = np.eye(V, dtype=np.float32)[ids[:, 1:]]

        def run(bucketed, scan, mode):
            lm = TransformerLM(vocab_size=V, d_model=32, n_layers=3,
                               n_heads=2, max_len=T)
            conf = lm.conf()
            conf.scan_layers = scan
            net = MultiLayerNetwork(conf).init(11)
            listener = CollectScoresListener()
            net.set_listeners(listener)
            ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing=mode, bucketed=bucketed).fit(
                x, y, epochs=3, batch_size=B, steps_per_execution=4)
            return np.asarray([s for _, s in listener.scores])

        for scan in (True, False):
            mono = run(False, scan, "dense")
            bkt = run(True, scan, "dense")
            np.testing.assert_allclose(bkt, mono, rtol=0, atol=2e-4,
                                       err_msg=f"scan_layers={scan}")
        thr = run(True, True, "threshold")
        assert thr[-1] < thr[0], f"bucketed threshold LM failed: {thr}"

    def test_dense_rs_bit_exact_vs_dense(self):
        """The ZeRO acceptance bar: dense_rs (reduce-scatter + sharded
        updater + all-gather) must match bucketed dense BIT-exactly on
        a 4-way mesh — params AND updater state, across steps where the
        rs plan genuinely shards."""
        mesh = make_mesh(MeshSpec.of(data=4))
        rng = np.random.default_rng(7)
        x = rng.standard_normal((128, 16)).astype(np.float32)
        w = rng.standard_normal((16, 4))
        y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]

        def run(mode):
            net = wide_mlp()
            t = ParallelTrainer(net, mesh, mode="sync",
                                gradient_sharing=mode)
            t.fit(x, y, epochs=3, batch_size=32)
            return net, t

        dense, _ = run("dense")
        rs_net, rs_t = run("dense_rs")
        plan = rs_t._rs_plan()
        assert any(v for lp in plan.values() for v in lp.values()), plan
        assert params_bitwise(dense.params, rs_net.params)
        assert params_bitwise(dense.updater_state, rs_net.updater_state)
        # the full per-layer updater view survives the shard round-trip
        assert rs_net.updater_state["1"]["W"]["m"].shape == (128, 128)

    def test_threshold_rs_learns_and_composes_with_fsdp_specs(self):
        """threshold_rs: int8 reduce-scatter + sharded updater. The rs
        plan built from fsdp_param_specs (the FSDP composition seam)
        must match the shape-derived default, the mode must learn, and
        per-bucket residual/τ must persist like the threshold mode's."""
        from deeplearning4j_tpu.parallel.tensor import fsdp_param_specs
        x, y = toy_data(n=256, seed=8)
        ds = DataSet(x, y)
        net = wide_mlp()
        init = float(net.score(ds))
        specs = fsdp_param_specs(net, axis_size=8)
        t = ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing="threshold_rs",
                            rs_param_specs=specs)
        assert t._rs_plan() == gs.rs_shard_plan(net.params, 8)
        t.fit(x, y, epochs=6, batch_size=32)
        got = float(net.score(ds))
        assert got < 0.7 * init, f"threshold_rs failed to learn: {init}->{got}"
        assert isinstance(t._thr_tau, dict)
        res = t.threshold_residual()
        assert res["1"]["W"].shape == (8, 128, 128)  # full-size residual

    def test_rs_mode_guards(self, monkeypatch):
        """rs modes: sync-only (env toggle degrades, explicit raises),
        elementwise-GN-only, rejected under ShardedParallelTrainer,
        serde accepts the mode strings."""
        with pytest.raises(ValueError, match="sync"):
            ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging",
                            gradient_sharing="dense_rs")
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "dense_rs")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging")
        assert t.gradient_sharing == "dense"
        monkeypatch.delenv("DL4J_GRADIENT_SHARING")
        # whole-layer gradient normalization cannot run on shards
        from deeplearning4j_tpu.nn.conf.builder import GradientNormalization
        net = deep_mlp(2)
        net.conf.gradient_normalization = \
            GradientNormalization.CLIP_L2_PER_LAYER
        net.conf.gradient_normalization_threshold = 1.0
        with pytest.raises(ValueError, match="elementwise"):
            ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing="threshold_rs")
        mesh = make_mesh(MeshSpec.of(data=4, model=2))
        with pytest.raises(NotImplementedError, match="fsdp_param_specs"):
            ShardedParallelTrainer(deep_mlp(2), mesh,
                                   gradient_sharing="dense_rs")
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3))
                .gradient_sharing("threshold_rs", threshold=5e-4)
                .build())
        back = type(conf).from_json(conf.to_json())
        assert back.gradient_sharing == "threshold_rs"

    def test_rs_wire_bytes_and_jaxpr(self):
        """rs comm accounting: reduce-scatter + param all-gather
        payloads, visible in the traced exchange as reduce_scatter /
        all_gather collectives."""
        from benchtools.hlo_cost import collective_table
        net = wide_mlp()
        n = 8
        plan = gs.rs_shard_plan(net.params, n)
        dense_b = gs.exchange_wire_bytes(net.params, "dense")
        rs_b = gs.exchange_wire_bytes(net.params, "dense_rs", n_workers=n)
        # grads move the same fp32 bytes; the param all-gather adds the
        # sharded fraction / n on top
        shard_elems = sum(
            int(np.prod(np.shape(net.params[lk][pn])))
            for lk in plan for pn, on in plan[lk].items() if on)
        assert rs_b == pytest.approx(dense_b + 4.0 * shard_elems / n)
        trs_b = gs.exchange_wire_bytes(net.params, "threshold_rs",
                                       n_workers=n)
        assert trs_b < rs_b  # int8 wire beats fp32
        tbl = collective_table(gs.exchange_jaxpr(net.params, "dense_rs", n))
        assert tbl["by_collective"]["reduce_scatter"]["count"] > 0
        assert tbl["by_collective"]["all_gather"]["count"] > 0
        tbl = collective_table(
            gs.exchange_jaxpr(net.params, "threshold_rs", n))
        assert tbl["by_collective"]["reduce_scatter"]["count"] > 0


class TestBucketedGraphContainer:
    def _graph(self, seed=9):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        adam = lambda: Adam(0.01)
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=16, n_out=16,
                                            activation="tanh",
                                            updater=adam()), "in")
                .add_layer("d2", DenseLayer(n_in=16, n_out=16,
                                            activation="tanh",
                                            updater=adam()), "d1")
                .add_layer("out", OutputLayer(n_in=16, n_out=4,
                                              activation="softmax",
                                              loss="mcxent",
                                              updater=adam()), "d2")
                .set_outputs("out").build())
        conf.seed = seed
        return ComputationGraph(conf).init(seed)

    def test_graph_bucketed_dense_tracks_single_barrier(self):
        """Single-in/out ComputationGraph through ParallelTrainer: the
        (default) bucketed dense path must train it — regression guard
        for the graph-container crash — and track the single-barrier
        program within fp tolerance."""
        x, y = toy_data(n=128, seed=9)
        ds = DataSet(x, y)
        init = float(self._graph().score(ds))

        def run(bucketed):
            net = self._graph()
            t = ParallelTrainer(net, device_mesh(), mode="sync",
                                bucketed=bucketed)
            assert t._is_graph and not t._multi_io_graph
            t.fit(x, y, epochs=4, batch_size=32)
            return float(net.score(ds))

        mono, bkt = run(False), run(True)
        assert bkt < 0.7 * init, f"graph bucketed dense failed: {init}->{bkt}"
        assert abs(bkt - mono) <= 1e-3 * max(1.0, init), (init, mono, bkt)

    def test_graph_bucketed_threshold_learns(self):
        x, y = toy_data(n=128, seed=10)
        ds = DataSet(x, y)
        net = self._graph()
        init = float(net.score(ds))
        t = ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        t.fit(x, y, epochs=4, batch_size=32)
        assert float(net.score(ds)) < init
        assert set(t._thr_tau.keys()) == set(net.params.keys())

    def test_multi_io_graph_falls_back_or_raises(self):
        """Multi-io graphs: dense silently keeps the GSPMD
        single-barrier program; the bucketed-only modes name the
        limitation instead of crashing mid-trace."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_in=8, n_out=8), "a")
                .add_layer("db", DenseLayer(n_in=8, n_out=8), "b")
                .add_layer("oa", OutputLayer(n_in=8, n_out=3), "da")
                .add_layer("ob", OutputLayer(n_in=8, n_out=3), "db")
                .set_outputs("oa", "ob").build())
        net = ComputationGraph(conf).init(3)
        t = ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        assert t._multi_io_graph
        with pytest.raises(NotImplementedError, match="single-"):
            t.fit(np.zeros((8, 8), np.float32),
                  np.zeros((8, 3), np.float32), epochs=1, batch_size=8)


class TestPartialManualScanProbe:
    def _reset(self, monkeypatch):
        monkeypatch.setattr(gs, "_partial_manual_scan_cache", None)

    def test_version_gate_never_compiles_on_crashy_jaxlib(self, monkeypatch):
        """jaxlib 0.4.x CHECK-aborts the process on the probe program —
        the version gate must answer False WITHOUT attempting it."""
        self._reset(monkeypatch)
        monkeypatch.setattr(gs, "_jaxlib_version", lambda: (0, 4, 36))
        monkeypatch.setattr(
            gs, "_probe_partial_manual_scan",
            lambda: (_ for _ in ()).throw(AssertionError("compiled!")))
        assert gs.partial_manual_scan_supported() is False

    def test_probe_runs_and_caches_on_new_jaxlib(self, monkeypatch):
        self._reset(monkeypatch)
        calls = []
        monkeypatch.setattr(gs, "_jaxlib_version", lambda: (0, 7, 0))
        monkeypatch.setattr(gs, "_probe_partial_manual_scan",
                            lambda: calls.append(1) or True)
        assert gs.partial_manual_scan_supported() is True
        assert gs.partial_manual_scan_supported() is True
        assert len(calls) == 1  # cached
        # a probe failure (partitioner raises) falls back to unrolled
        self._reset(monkeypatch)
        monkeypatch.setattr(
            gs, "_probe_partial_manual_scan",
            lambda: (_ for _ in ()).throw(RuntimeError("partitioner")))
        assert gs.partial_manual_scan_supported() is False

    def test_current_jaxlib_resolves_without_crashing(self, monkeypatch):
        """Whatever jaxlib the environment ships, the probe must
        resolve to a bool without killing the process."""
        self._reset(monkeypatch)
        assert gs.partial_manual_scan_supported() in (True, False)

    def test_sharded_trainer_threads_probe_into_allow_scan(self,
                                                           monkeypatch):
        """The DP x TP step must trace with scan-over-layers exactly
        when the probe says the partitioner survives it."""
        captured = {}
        real = gs.make_bucketed_step

        def spy(model, axis, cfg, **kw):
            captured["allow_scan"] = kw.get("allow_scan")
            return real(model, axis, cfg, **kw)

        monkeypatch.setattr(gs, "make_bucketed_step", spy)
        mesh = make_mesh(MeshSpec.of(data=4, model=2))
        for supported in (False, True):
            monkeypatch.setattr(gs, "partial_manual_scan_supported",
                                lambda s=supported: s)
            t = ShardedParallelTrainer(deep_mlp(3), mesh,
                                       gradient_sharing="threshold")
            t._build_threshold()
            assert captured["allow_scan"] is supported
        # pure-DP (no auto axes) always scans, probe irrelevant
        monkeypatch.setattr(gs, "partial_manual_scan_supported",
                            lambda: False)
        net = deep_mlp(3)
        from jax.sharding import PartitionSpec as P
        repl_specs = {lk: {pn: P() for pn in lp}
                      for lk, lp in net.params.items()}
        t = ShardedParallelTrainer(
            net, make_mesh(MeshSpec.of(data=8)),
            gradient_sharing="threshold", param_specs=repl_specs)
        t._build_threshold()
        assert captured["allow_scan"] is True


# ------------------------------------------------------- comm-bytes accounting
class TestCommAccounting:
    def test_exchange_jaxpr_bytes(self):
        """The traced exchange programs carry the wire contract: dense
        moves 4 bytes/element, threshold 1 byte/element (+ scalars)."""
        from benchtools.hlo_cost import collective_table
        net = deep_mlp(2)
        elems = sum(int(np.prod(np.shape(l)))
                    for l in jax.tree_util.tree_leaves(net.params))
        dense = collective_table(gs.exchange_jaxpr(net.params, "dense", 8))
        thr = collective_table(gs.exchange_jaxpr(net.params, "threshold", 8))
        assert dense["comm_bytes_per_step"] == 4 * elems
        assert thr["comm_bytes_per_step"] == elems + 4  # + sent-count psum
        assert dense["by_collective"]["all_reduce"]["count"] > 0
        ratio = dense["comm_bytes_per_step"] / thr["comm_bytes_per_step"]
        assert ratio > 3.5

    def test_wire_bytes_accounting(self):
        net = deep_mlp(2)
        elems = sum(int(np.prod(np.shape(l)))
                    for l in jax.tree_util.tree_leaves(net.params))
        assert gs.exchange_wire_bytes(net.params, "dense") == 4 * elems
        assert gs.exchange_wire_bytes(net.params, "threshold",
                                      n_workers=8) == elems + 8
        # int16 widening beyond 127 replicas
        assert gs.exchange_wire_bytes(net.params, "threshold",
                                      n_workers=200) == 2 * elems + 8

    def test_comm_bytes_block_and_gauges(self):
        """hlo_cost's program-section block + the aot_comm_bytes_*
        gauges the /metrics route serves."""
        from benchtools import hlo_cost
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import MetricsRegistry, xprof
        net = deep_mlp(2)
        blk = hlo_cost.comm_bytes_block(net, n_workers=8)
        assert "error" not in blk, blk
        assert blk["threshold_bytes_per_step"] < blk["dense_bytes_per_step"]
        assert blk["reduction"] >= 3.5
        reg = MetricsRegistry()
        xprof.publish_cost_report(
            {"model": "gs_test", "program": {"comm_bytes": blk}},
            registry=reg)
        expo = reg.exposition()
        assert 'aot_comm_bytes_dense{model="gs_test"}' in expo
        assert 'aot_comm_bytes_threshold{model="gs_test"}' in expo
        assert 'aot_comm_bytes_reduction{model="gs_test"}' in expo

    def test_trainer_comm_counters(self):
        """The trainers count exchanged bytes + compression ratio on the
        monitor registry (host math, both modes)."""
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        try:
            x, y = toy_data(n=64, seed=3)
            for mode in ("dense", "threshold"):
                net = deep_mlp(2)
                ParallelTrainer(net, device_mesh(), mode="sync",
                                gradient_sharing=mode).fit(
                    x, y, epochs=1, batch_size=32)
            expo = reg.exposition()
            assert 'gradient_exchange_bytes_total{mode="dense"' in expo
            assert 'gradient_exchange_bytes_total{mode="threshold"' in expo
            assert "gradient_sharing_compression_ratio" in expo
            assert "gradient_sharing_threshold" in expo
            assert "gradient_sharing_sparsity" in expo
            snap = reg.snapshot()["gradient_exchange_bytes_total"]["values"]
            by_mode = {e["labels"]["mode"]: e["value"] for e in snap}
            assert by_mode["dense"] > by_mode["threshold"] * 3.5
        finally:
            monitor.disable()
