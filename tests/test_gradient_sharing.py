"""Threshold-encoded gradient sharing (parallel/gradient_sharing.py):
encode/decode/error-feedback units, adaptive-τ controller, mode
resolution + conf serde, convergence parity vs dense sync training
(deep MLP with packed ``stacked::`` runs, TransformerLM with
scan_layers + fused multi-step, DP x TP), and the comm-bytes
accounting seam (benchtools/hlo_cost.collective_table /
comm_bytes_block)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresListener
from deeplearning4j_tpu.parallel import gradient_sharing as gs
from deeplearning4j_tpu.parallel.mesh import MeshSpec, device_mesh, make_mesh
from deeplearning4j_tpu.parallel.tensor import ShardedParallelTrainer
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer


def deep_mlp(n_hidden=6, seed=7, lr=0.01):
    """Deep homogeneous MLP — the hidden stack forms ONE scan run that
    packs at the train-step boundary (stacked:: entries)."""
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr)).list()
    for _ in range(n_hidden):
        b = b.layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
    conf = (b.layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def toy_data(n=320, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 16)).astype(np.float32)
    w = rng.standard_normal((16, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


# ---------------------------------------------------------------- unit level
class TestEncodeDecode:
    def test_error_feedback_identity(self):
        """enc*τ + residual == grad + old residual, exactly: nothing is
        ever lost to the compression."""
        rng = np.random.default_rng(3)
        acc = rng.standard_normal((64,)).astype(np.float32) * 0.01
        tau = jnp.float32(0.005)
        enc, res, sent = gs.encode_leaf(jnp.asarray(acc), tau, jnp.int8)
        rebuilt = (np.asarray(enc).astype(np.float32) * np.float32(0.005)
                   + np.asarray(res))
        np.testing.assert_allclose(rebuilt, acc, rtol=0, atol=1e-8)
        assert np.asarray(enc).dtype == np.int8
        assert set(np.unique(np.asarray(enc))) <= {-1, 0, 1}
        assert float(sent) == float(np.sum(np.abs(acc) >= 0.005))

    def test_wire_dtype(self):
        assert gs.wire_dtype(8) == jnp.int8
        assert gs.wire_dtype(127) == jnp.int8
        assert gs.wire_dtype(128) == jnp.int16
        with pytest.raises(ValueError, match="32767"):
            gs.wire_dtype(40000)

    def test_adapt_threshold_band(self):
        cfg = gs.ThresholdConfig()
        tau = jnp.float32(1e-3)
        # above the band: boost (send less)
        up = gs.adapt_threshold(tau, jnp.float32(0.5), cfg)
        assert float(up) == pytest.approx(1e-3 * cfg.boost)
        # below the band: decay (send more)
        down = gs.adapt_threshold(tau, jnp.float32(1e-5), cfg)
        assert float(down) == pytest.approx(1e-3 * cfg.decay)
        # inside: unchanged
        mid = gs.adapt_threshold(tau, jnp.float32(0.05), cfg)
        assert float(mid) == pytest.approx(1e-3)
        # clamp
        lo = gs.adapt_threshold(jnp.float32(1e-8), jnp.float32(0.0), cfg)
        assert float(lo) >= float(np.float32(cfg.min_threshold))

    def test_config_validation(self):
        with pytest.raises(ValueError, match="band"):
            gs.ThresholdConfig(sparsity_target_min=0.5,
                               sparsity_target_max=0.1)
        with pytest.raises(ValueError, match="decay"):
            gs.ThresholdConfig(decay=1.5)
        with pytest.raises(ValueError, match="min_threshold"):
            gs.ThresholdConfig(initial_threshold=2.0)


class TestModeResolution:
    def test_precedence(self, monkeypatch):
        conf = deep_mlp(2).conf
        assert gs.resolve_mode(None, conf) == "dense"
        conf.gradient_sharing = "threshold"
        assert gs.resolve_mode(None, conf) == "threshold"
        assert gs.resolve_mode("dense", conf) == "dense"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "threshold")
        assert gs.resolve_mode("dense", conf) == "threshold"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "0")
        assert gs.resolve_mode("threshold", conf) == "dense"
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "bogus")
        with pytest.raises(ValueError, match="DL4J_GRADIENT_SHARING"):
            gs.resolve_mode(None, conf)

    def test_env_override_reaches_trainer(self, monkeypatch):
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "dense")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        assert t.gradient_sharing == "dense"

    def test_threshold_rejects_averaging_mode(self):
        with pytest.raises(ValueError, match="sync"):
            ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging",
                            gradient_sharing="threshold")

    def test_env_toggle_degrades_gracefully_for_averaging(self, monkeypatch):
        """The global DL4J_GRADIENT_SHARING=threshold A/B toggle must
        not crash unrelated averaging-mode trainers (it falls back to
        dense there); only an EXPLICIT arg/conf request hard-errors."""
        monkeypatch.setenv("DL4J_GRADIENT_SHARING", "threshold")
        t = ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging")
        assert t.gradient_sharing == "dense"
        with pytest.raises(ValueError, match="sync"):
            ParallelTrainer(deep_mlp(2), device_mesh(), mode="averaging",
                            gradient_sharing="threshold")

    def test_mlc_serde_round_trip(self):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3))
                .gradient_sharing("threshold", threshold=5e-4)
                .build())
        assert conf.gradient_sharing == "threshold"
        assert conf.gradient_sharing_threshold == 5e-4
        back = type(conf).from_json(conf.to_json())
        assert back.gradient_sharing == "threshold"
        assert back.gradient_sharing_threshold == 5e-4
        # trainer picks the conf flag + τ0 up
        net = MultiLayerNetwork(back).init()
        t = ParallelTrainer(net, device_mesh(), mode="sync")
        assert t.gradient_sharing == "threshold"
        assert t.threshold_config.initial_threshold == 5e-4

    def test_graph_serde_round_trip(self):
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=3), "d")
                .set_outputs("out")
                .gradient_sharing("threshold", threshold=2e-3)
                .build())
        back = ComputationGraphConfiguration.from_json(conf.to_json())
        assert back.gradient_sharing == "threshold"
        assert back.gradient_sharing_threshold == 2e-3
        with pytest.raises(ValueError, match="dense|threshold"):
            (ComputationGraphConfiguration.graph_builder()
             .gradient_sharing("sparse"))


# --------------------------------------------------------- convergence parity
class TestConvergenceParity:
    def test_deep_mlp_threshold_tracks_dense(self):
        """Deep MLP (one packed stacked:: run), 50 sync steps: threshold
        with error feedback must learn and stay within tolerance of the
        dense trajectory; the per-replica residual must survive the
        pack/unpack boundary with per-LAYER keys."""
        x, y = toy_data()
        ds = DataSet(x, y)
        init = float(deep_mlp().score(ds))

        dense = deep_mlp()
        ParallelTrainer(dense, device_mesh(), mode="sync").fit(
            x, y, epochs=5, batch_size=32)
        thr = deep_mlp()
        t = ParallelTrainer(thr, device_mesh(), mode="sync",
                            gradient_sharing="threshold")
        t.fit(x, y, epochs=5, batch_size=32)

        d, th = float(dense.score(ds)), float(thr.score(ds))
        assert d < 0.5 * init, f"dense failed to learn {init}->{d}"
        assert th < 0.5 * init, f"threshold failed to learn {init}->{th}"
        assert abs(th - d) <= 0.35 * init, (init, d, th)

        # residual: per-layer keys (stacked:: packing never leaks out),
        # per-replica leading axis, and nonzero — error feedback active
        res = t.threshold_residual()
        assert set(res.keys()) == set(thr.params.keys())
        assert not any(k.startswith("stacked::") for k in res)
        lead = res["0"]["W"].shape
        assert lead == (t.n_workers,) + thr.params["0"]["W"].shape
        assert any(float(np.abs(l).max()) > 0
                   for l in jax.tree_util.tree_leaves(res))
        # τ adapted away from its initial value
        assert float(np.asarray(t._thr_tau)) != pytest.approx(
            t.threshold_config.initial_threshold)

    def test_fused_multi_step_bit_identical(self):
        """steps_per_execution>1 (residual + τ riding the scan carry)
        must reproduce the per-step trajectory exactly — same numeric
        contract the dense fused path keeps."""
        x, y = toy_data(n=256, seed=1)

        def run(spe):
            net = deep_mlp(4)
            listener = CollectScoresListener()
            net.set_listeners(listener)
            t = ParallelTrainer(net, device_mesh(), mode="sync",
                                gradient_sharing="threshold")
            t.fit(x, y, epochs=3, batch_size=32, steps_per_execution=spe)
            return ([s for _, s in listener.scores],
                    float(np.asarray(t._thr_tau)))

        per_step, tau1 = run(1)
        fused, tau4 = run(4)
        assert len(per_step) == len(fused) == 24
        np.testing.assert_allclose(per_step, fused, rtol=0, atol=0)
        assert tau1 == tau4

    def test_transformer_lm_threshold_tracks_dense(self):
        """TransformerLM with scan_layers on + fused multi-step: the
        threshold exchange must hold convergence parity through the
        scan-compiled, boundary-packed program."""
        from deeplearning4j_tpu.zoo.transformer import TransformerLM
        B, T, V = 16, 16, 37
        rng = np.random.default_rng(5)
        ids = rng.integers(0, V, (B * 4, T + 1))
        x = ids[:, :-1].astype(np.float32)
        y = np.eye(V, dtype=np.float32)[ids[:, 1:]]

        def build():
            lm = TransformerLM(vocab_size=V, d_model=32, n_layers=3,
                               n_heads=2, max_len=T)
            conf = lm.conf()
            assert conf.scan_layers
            net = MultiLayerNetwork(conf).init(11)
            return net

        def run(mode):
            net = build()
            listener = CollectScoresListener()
            net.set_listeners(listener)
            ParallelTrainer(net, device_mesh(), mode="sync",
                            gradient_sharing=mode).fit(
                x, y, epochs=6, batch_size=B, steps_per_execution=4)
            return [s for _, s in listener.scores]

        dense = run("dense")
        thr = run("threshold")
        assert len(dense) == len(thr) == 24
        assert dense[-1] < dense[0]
        assert thr[-1] < thr[0], f"threshold LM failed to learn: {thr}"
        # parity band: same scale of progress from the same start
        assert abs(thr[-1] - dense[-1]) <= 0.35 * dense[0], (dense, thr)

    def test_sharded_dp_tp_threshold(self):
        """DP x TP (auto model axis): the compressed data-axis exchange
        composes with GSPMD tensor parallelism."""
        x, y = toy_data(n=256, seed=2)
        ds = DataSet(x, y)
        mesh = make_mesh(MeshSpec.of(data=4, model=2))
        init = float(deep_mlp(3).score(ds))

        thr = deep_mlp(3)
        t = ShardedParallelTrainer(thr, mesh, gradient_sharing="threshold")
        t.fit(x, y, epochs=6, batch_size=32)
        th = float(thr.score(ds))
        assert th < 0.6 * init, f"TP threshold failed to learn {init}->{th}"
        assert t._thr_residual_r is not None
        assert float(np.asarray(t._thr_tau)) > 0


# ------------------------------------------------------- comm-bytes accounting
class TestCommAccounting:
    def test_exchange_jaxpr_bytes(self):
        """The traced exchange programs carry the wire contract: dense
        moves 4 bytes/element, threshold 1 byte/element (+ scalars)."""
        from benchtools.hlo_cost import collective_table
        net = deep_mlp(2)
        elems = sum(int(np.prod(np.shape(l)))
                    for l in jax.tree_util.tree_leaves(net.params))
        dense = collective_table(gs.exchange_jaxpr(net.params, "dense", 8))
        thr = collective_table(gs.exchange_jaxpr(net.params, "threshold", 8))
        assert dense["comm_bytes_per_step"] == 4 * elems
        assert thr["comm_bytes_per_step"] == elems + 4  # + sent-count psum
        assert dense["by_collective"]["all_reduce"]["count"] > 0
        ratio = dense["comm_bytes_per_step"] / thr["comm_bytes_per_step"]
        assert ratio > 3.5

    def test_wire_bytes_accounting(self):
        net = deep_mlp(2)
        elems = sum(int(np.prod(np.shape(l)))
                    for l in jax.tree_util.tree_leaves(net.params))
        assert gs.exchange_wire_bytes(net.params, "dense") == 4 * elems
        assert gs.exchange_wire_bytes(net.params, "threshold",
                                      n_workers=8) == elems + 8
        # int16 widening beyond 127 replicas
        assert gs.exchange_wire_bytes(net.params, "threshold",
                                      n_workers=200) == 2 * elems + 8

    def test_comm_bytes_block_and_gauges(self):
        """hlo_cost's program-section block + the aot_comm_bytes_*
        gauges the /metrics route serves."""
        from benchtools import hlo_cost
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import MetricsRegistry, xprof
        net = deep_mlp(2)
        blk = hlo_cost.comm_bytes_block(net, n_workers=8)
        assert "error" not in blk, blk
        assert blk["threshold_bytes_per_step"] < blk["dense_bytes_per_step"]
        assert blk["reduction"] >= 3.5
        reg = MetricsRegistry()
        xprof.publish_cost_report(
            {"model": "gs_test", "program": {"comm_bytes": blk}},
            registry=reg)
        expo = reg.exposition()
        assert 'aot_comm_bytes_dense{model="gs_test"}' in expo
        assert 'aot_comm_bytes_threshold{model="gs_test"}' in expo
        assert 'aot_comm_bytes_reduction{model="gs_test"}' in expo

    def test_trainer_comm_counters(self):
        """The trainers count exchanged bytes + compression ratio on the
        monitor registry (host math, both modes)."""
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import MetricsRegistry
        reg = monitor.enable(registry=MetricsRegistry())
        try:
            x, y = toy_data(n=64, seed=3)
            for mode in ("dense", "threshold"):
                net = deep_mlp(2)
                ParallelTrainer(net, device_mesh(), mode="sync",
                                gradient_sharing=mode).fit(
                    x, y, epochs=1, batch_size=32)
            expo = reg.exposition()
            assert 'gradient_exchange_bytes_total{mode="dense"' in expo
            assert 'gradient_exchange_bytes_total{mode="threshold"' in expo
            assert "gradient_sharing_compression_ratio" in expo
            assert "gradient_sharing_threshold" in expo
            assert "gradient_sharing_sparsity" in expo
            snap = reg.snapshot()["gradient_exchange_bytes_total"]["values"]
            by_mode = {e["labels"]["mode"]: e["value"] for e in snap}
            assert by_mode["dense"] > by_mode["threshold"] * 3.5
        finally:
            monitor.disable()
