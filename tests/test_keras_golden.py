"""Golden-file Keras import tests against h5py-written fixtures in the
real Keras 1/2 on-disk layouts, with numpy-computed expected outputs —
output parity, not just shape equality (reference pattern:
`modelimport/src/test/resources/configs/` golden files +
`Keras2ModelConfigurationTest`)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import KerasModelImport
from tests.keras_fixture_util import (
    np_conv2d_same,
    np_lstm,
    np_maxpool2d,
    np_separable_conv2d_valid,
    np_softmax,
    write_keras1_h5,
    write_keras2_h5,
)


def _seq_config(layers):
    return {"class_name": "Sequential",
            "config": {"name": "sequential", "layers": layers}}


class TestKeras2Golden:
    def test_cnn_output_parity(self, tmp_path):
        rng = np.random.default_rng(0)
        kconv = rng.standard_normal((3, 3, 1, 4)).astype(np.float32) * 0.3
        bconv = rng.standard_normal(4).astype(np.float32) * 0.1
        kd = rng.standard_normal((4 * 4 * 4, 10)).astype(np.float32) * 0.2
        bd = rng.standard_normal(10).astype(np.float32) * 0.1
        cfg = _seq_config([
            {"class_name": "Conv2D",
             "config": {"name": "conv", "filters": 4, "kernel_size": [3, 3],
                        "strides": [1, 1], "padding": "same",
                        "activation": "relu", "use_bias": True,
                        "batch_input_shape": [None, 8, 8, 1]}},
            {"class_name": "MaxPooling2D",
             "config": {"name": "pool", "pool_size": [2, 2],
                        "strides": [2, 2], "padding": "valid"}},
            {"class_name": "Flatten", "config": {"name": "flatten"}},
            {"class_name": "Dense",
             "config": {"name": "fc", "units": 10, "activation": "softmax",
                        "use_bias": True}},
        ])
        path = tmp_path / "cnn.h5"
        write_keras2_h5(path, cfg, [
            ("conv", [("kernel", kconv), ("bias", bconv)]),
            ("pool", []), ("flatten", []),
            ("fc", [("kernel", kd), ("bias", bd)]),
        ])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = rng.standard_normal((2, 8, 8, 1)).astype(np.float32)
        got = np.asarray(net.output(x))
        h = np.maximum(np_conv2d_same(x, kconv, bconv), 0.0)
        h = np_maxpool2d(h, 2)
        want = np_softmax(h.reshape(2, -1) @ kd + bd)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_lstm_output_parity(self, tmp_path):
        rng = np.random.default_rng(1)
        U, F, T = 5, 3, 4
        K = rng.standard_normal((F, 4 * U)).astype(np.float32) * 0.4
        R = rng.standard_normal((U, 4 * U)).astype(np.float32) * 0.4
        b = rng.standard_normal(4 * U).astype(np.float32) * 0.1
        kd = rng.standard_normal((U, 2)).astype(np.float32)
        bd = np.zeros(2, np.float32)
        cfg = _seq_config([
            {"class_name": "LSTM",
             "config": {"name": "lstm", "units": U, "activation": "tanh",
                        "recurrent_activation": "hard_sigmoid",
                        "return_sequences": False,
                        "batch_input_shape": [None, T, F]}},
            {"class_name": "Dense",
             "config": {"name": "fc", "units": 2, "activation": "softmax"}},
        ])
        path = tmp_path / "lstm.h5"
        write_keras2_h5(path, cfg, [
            ("lstm", [("kernel", K), ("recurrent_kernel", R), ("bias", b)]),
            ("fc", [("kernel", kd), ("bias", bd)]),
        ])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = rng.standard_normal((2, T, F)).astype(np.float32)
        got = np.asarray(net.output(x))
        want = np_softmax(np_lstm(x, K, R, b) @ kd + bd)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_separable_conv_output_parity(self, tmp_path):
        rng = np.random.default_rng(2)
        dk = rng.standard_normal((3, 3, 2, 2)).astype(np.float32) * 0.3
        pk = rng.standard_normal((1, 1, 4, 5)).astype(np.float32) * 0.3
        b = rng.standard_normal(5).astype(np.float32) * 0.1
        cfg = _seq_config([
            {"class_name": "SeparableConv2D",
             "config": {"name": "sep", "filters": 5, "kernel_size": [3, 3],
                        "strides": [1, 1], "padding": "valid",
                        "depth_multiplier": 2, "activation": "linear",
                        "use_bias": True,
                        "batch_input_shape": [None, 6, 6, 2]}},
            {"class_name": "Flatten", "config": {"name": "flatten"}},
            {"class_name": "Dense",
             "config": {"name": "fc", "units": 3, "activation": "softmax"}},
        ])
        kd = rng.standard_normal((4 * 4 * 5, 3)).astype(np.float32) * 0.1
        bd = np.zeros(3, np.float32)
        path = tmp_path / "sep.h5"
        write_keras2_h5(path, cfg, [
            ("sep", [("depthwise_kernel", dk), ("pointwise_kernel", pk),
                     ("bias", b)]),
            ("flatten", []),
            ("fc", [("kernel", kd), ("bias", bd)]),
        ])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = rng.standard_normal((2, 6, 6, 2)).astype(np.float32)
        got = np.asarray(net.output(x))
        h = np_separable_conv2d_valid(x, dk, pk, b)
        want = np_softmax(h.reshape(2, -1) @ kd + bd)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_shape_op_layers_import(self, tmp_path):
        # Reshape / Permute / ZeroPadding1D / Upsampling1D / dilated conv
        rng = np.random.default_rng(3)
        cfg = _seq_config([
            {"class_name": "Reshape",
             "config": {"name": "rs", "target_shape": [4, 6],
                        "batch_input_shape": [None, 24]}},
            {"class_name": "Permute", "config": {"name": "pm", "dims": [2, 1]}},
            {"class_name": "ZeroPadding1D",
             "config": {"name": "zp", "padding": [1, 1]}},
            {"class_name": "UpSampling1D", "config": {"name": "up", "size": 2}},
            {"class_name": "Conv1D",
             "config": {"name": "conv", "filters": 3, "kernel_size": [3],
                        "strides": [1], "padding": "valid",
                        "dilation_rate": [2], "activation": "relu"}},
        ])
        kc = rng.standard_normal((3, 4, 3)).astype(np.float32) * 0.3
        bc = np.zeros(3, np.float32)
        path = tmp_path / "shapes.h5"
        write_keras2_h5(path, cfg, [
            ("rs", []), ("pm", []), ("zp", []), ("up", []),
            ("conv", [("kernel", kc), ("bias", bc)]),
        ])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = rng.standard_normal((2, 24)).astype(np.float32)
        out = np.asarray(net.output(x))
        # 24 → [4,6] → permute [6,4] → pad T 6+2=8 → upsample T=16 →
        # dilated k=3 d=2 valid: 16 - (3 + 2*1 - 1) + 1 = 12
        assert out.shape == (2, 12, 3)

    def test_upsampling1d_keras_name_variant(self, tmp_path):
        # Keras 1 spells it "UpSampling1D" too but with length= key
        cfg = _seq_config([
            {"class_name": "UpSampling1D",
             "config": {"name": "up", "length": 3,
                        "batch_input_shape": [None, 4, 2]}},
        ])
        path = tmp_path / "up1.h5"
        write_keras2_h5(path, cfg, [("up", [])])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = np.random.randn(1, 4, 2).astype(np.float32)
        assert np.asarray(net.output(x)).shape == (1, 12, 2)


class TestKeras1Golden:
    def test_dense_mlp_keras1_dialect(self, tmp_path):
        rng = np.random.default_rng(4)
        W1 = rng.standard_normal((6, 8)).astype(np.float32) * 0.4
        b1 = rng.standard_normal(8).astype(np.float32) * 0.1
        W2 = rng.standard_normal((8, 3)).astype(np.float32) * 0.4
        b2 = np.zeros(3, np.float32)
        cfg = {"class_name": "Sequential", "config": [
            {"class_name": "Dense",
             "config": {"name": "dense_1", "output_dim": 8,
                        "activation": "tanh", "input_dim": 6}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "output_dim": 3,
                        "activation": "softmax"}},
        ]}
        path = tmp_path / "k1.h5"
        write_keras1_h5(path, cfg, [
            ("dense_1", [("W", W1), ("b", b1)]),
            ("dense_2", [("W", W2), ("b", b2)]),
        ])
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        x = rng.standard_normal((3, 6)).astype(np.float32)
        got = np.asarray(net.output(x))
        want = np_softmax(np.tanh(x @ W1 + b1) @ W2 + b2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestZooPretrained:
    def test_init_pretrained_roundtrip_via_file_url(self, tmp_path):
        """init_pretrained: URL → cache → checksum → Keras import →
        working model (reference ZooModel.initPretrained :52-81),
        driven by a file:// URL so it runs offline."""
        import hashlib

        from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel

        rng = np.random.default_rng(5)
        W = rng.standard_normal((4, 2)).astype(np.float32)
        b = np.zeros(2, np.float32)
        cfg = _seq_config([
            {"class_name": "Dense",
             "config": {"name": "fc", "units": 2, "activation": "softmax",
                        "batch_input_shape": [None, 4]}},
        ])
        h5path = tmp_path / "tiny_pretrained.h5"
        write_keras2_h5(h5path, cfg, [("fc", [("kernel", W), ("bias", b)])])
        checksum = hashlib.sha256(h5path.read_bytes()).hexdigest()

        class TinyZoo(ZooModel):
            def pretrained_url(self, ptype):
                return h5path.as_uri()

            def pretrained_checksum(self, ptype):
                return checksum

        net = TinyZoo().init_pretrained(PretrainedType.IMAGENET)
        x = rng.standard_normal((3, 4)).astype(np.float32)
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, np_softmax(x @ W + b),
                                   rtol=1e-5, atol=1e-6)

    def test_vgg16_resnet50_urls_wired(self):
        from deeplearning4j_tpu.zoo.base import PretrainedType
        from deeplearning4j_tpu.zoo.resnet50 import ResNet50
        from deeplearning4j_tpu.zoo.vgg import VGG16

        for cls in (VGG16, ResNet50):
            m = cls()
            url = m.pretrained_url(PretrainedType.IMAGENET)
            assert url and url.endswith(".h5")
            assert m.pretrained_checksum(PretrainedType.IMAGENET)


class TestWeightsOnlyH5:
    """keras-applications distribution format: no model_config attr —
    weights are order-matched into an already-built network."""

    def _write_weights_only(self, path, layer_weights):
        import h5py
        with h5py.File(path, "w") as f:
            f.attrs["layer_names"] = np.array(
                [ln.encode() for ln, _ in layer_weights], dtype="S64")
            f.attrs["backend"] = b"tensorflow"
            for lname, weights in layer_weights:
                g = f.create_group(lname)
                wnames = [f"{lname}/{wn}:0" for wn, _ in weights]
                g.attrs["weight_names"] = np.array(
                    [w.encode() for w in wnames], dtype="S128")
                for (wn, arr), full in zip(weights, wnames):
                    g.create_dataset(full, data=np.asarray(arr, np.float32))

    def _tiny_net(self):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import (
            ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=6, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        return MultiLayerNetwork(conf).init()

    def test_load_weights_into_order_matched(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        rng = np.random.default_rng(7)
        net = self._tiny_net()
        kc = rng.standard_normal((3, 3, 1, 4)).astype(np.float32)
        bc = rng.standard_normal(4).astype(np.float32)
        kd = rng.standard_normal((4 * 4 * 4, 6)).astype(np.float32)
        bd = rng.standard_normal(6).astype(np.float32)
        ko = rng.standard_normal((6, 2)).astype(np.float32)
        bo = np.zeros(2, np.float32)
        path = tmp_path / "weights_only.h5"
        self._write_weights_only(path, [
            ("block1_conv1", [("kernel", kc), ("bias", bc)]),
            ("pool", []),
            ("fc1", [("kernel", kd), ("bias", bd)]),
            ("predictions", [("kernel", ko), ("bias", bo)]),
        ])
        KerasModelImport.load_weights_into(net, str(path))
        np.testing.assert_allclose(np.asarray(net.params["0"]["W"]), kc)
        np.testing.assert_allclose(np.asarray(net.params["2"]["W"]), kd)
        np.testing.assert_allclose(np.asarray(net.params["3"]["W"]), ko)

    def test_load_weights_into_topology_mismatch_raises(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        net = self._tiny_net()
        path = tmp_path / "short.h5"
        self._write_weights_only(path, [
            ("only_one", [("kernel", np.zeros((3, 3, 1, 4), np.float32))]),
        ])
        with pytest.raises(ValueError, match="topologies differ"):
            KerasModelImport.load_weights_into(net, str(path))

    def test_init_pretrained_weights_only_route(self, tmp_path):
        import hashlib
        from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel
        rng = np.random.default_rng(8)
        outer = self

        kc = rng.standard_normal((3, 3, 1, 4)).astype(np.float32)
        bc = np.zeros(4, np.float32)
        kd = rng.standard_normal((4 * 4 * 4, 6)).astype(np.float32)
        bd = np.zeros(6, np.float32)
        ko = rng.standard_normal((6, 2)).astype(np.float32)
        bo = np.zeros(2, np.float32)
        path = tmp_path / "zoo_weights.h5"
        self._write_weights_only(path, [
            ("c", [("kernel", kc), ("bias", bc)]),
            ("d", [("kernel", kd), ("bias", bd)]),
            ("o", [("kernel", ko), ("bias", bo)]),
        ])
        checksum = hashlib.sha256(path.read_bytes()).hexdigest()

        class TinyZoo(ZooModel):
            def init(self):
                return outer._tiny_net()

            def pretrained_url(self, ptype):
                return path.as_uri()

            def pretrained_checksum(self, ptype):
                return checksum

        net = TinyZoo().init_pretrained(PretrainedType.IMAGENET)
        np.testing.assert_allclose(np.asarray(net.params["0"]["W"]), kc)


class TestDimOrderingDetection:
    def test_keras1_th_dim_ordering_keeps_nchw_flatten(self, tmp_path):
        """A Theano-ordering file must flatten channel-major even when
        the config shape heuristic would guess channels_last."""
        rng = np.random.default_rng(9)
        # input 4x4x2 NHWC; conv 1x1 identity-ish; flatten; dense
        kconv = rng.standard_normal((1, 1, 2, 2)).astype(np.float32)
        bconv = np.zeros(2, np.float32)
        kd = rng.standard_normal((32, 3)).astype(np.float32)
        bd = np.zeros(3, np.float32)
        cfg = {"class_name": "Model",  # functional dict config, Keras 1
               "config": {"name": "m", "layers": [
                   {"class_name": "InputLayer", "name": "in",
                    "config": {"name": "in",
                               "batch_input_shape": [None, 4, 4, 2]},
                    "inbound_nodes": []},
                   {"class_name": "Convolution2D", "name": "conv",
                    "config": {"name": "conv", "nb_filter": 2, "nb_row": 1,
                               "nb_col": 1, "dim_ordering": "th",
                               "border_mode": "valid",
                               "activation": "linear"},
                    "inbound_nodes": [[["in", 0, 0]]]},
                   {"class_name": "Flatten", "name": "flat",
                    "config": {"name": "flat"},
                    "inbound_nodes": [[["conv", 0, 0]]]},
                   {"class_name": "Dense", "name": "fc",
                    "config": {"name": "fc", "output_dim": 3,
                               "activation": "softmax"},
                    "inbound_nodes": [[["flat", 0, 0]]]},
               ], "input_layers": [["in", 0, 0]],
                   "output_layers": [["fc", 0, 0]]}}
        from tests.keras_fixture_util import write_keras2_h5
        import h5py
        path = tmp_path / "th.h5"
        write_keras2_h5(path, cfg, [
            ("conv", [("kernel", kconv), ("bias", bconv)]),
            ("fc", [("kernel", kd), ("bias", bd)]),
        ])
        with h5py.File(path, "a") as f:  # strip the backend attr
            del f.attrs["backend"]
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        net = KerasModelImport.import_keras_model_and_weights(str(path))
        pp = [n.preprocessor for n in net.conf.nodes.values()
              if n.preprocessor is not None]
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToFeedForwardPreProcessor,
        )
        flat_pps = [p for p in pp
                    if isinstance(p, CnnToFeedForwardPreProcessor)]
        assert flat_pps and all(p.data_format == "nchw" for p in flat_pps)
