"""Pin the DOCUMENTED fused-vs-per-step net_state divergence
(`nn/multilayer.py` `_multi_step_fn`): the scan carry keeps a constant
pytree structure, so state keys a train forward emits that were absent
at init (MoE's functional aux-loss slot) are not carried across fused
steps, while the per-step path merges them into net_state outside jit.

If a future layer puts MEANINGFUL dynamic state in such keys, these
assertions fail loudly instead of the state being silently lost."""

import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    MixtureOfExperts,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _moe_net():
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(MixtureOfExperts(n_experts=2, hidden_size=8, top_k=1))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def _dynamic_entries(net):
    """net_state entries (layer slots and keys) absent at a fresh init.
    MoE's aux_loss is popped by the container's loss fn, so what the
    per-step merge leaves behind is the popped-EMPTY layer slot."""
    fresh = _moe_net()
    out = []
    for lk, st in net.net_state.items():
        if lk not in fresh.net_state:
            out.append((lk, sorted(st)))
        else:
            extra = set(st) - set(fresh.net_state[lk])
            if extra:
                out.append((lk, sorted(extra)))
    return out


class TestFusedStateParity:
    def test_per_step_path_merges_dynamic_state(self):
        net = _moe_net()
        x, y = _data()
        net.fit(x, y, epochs=1, batch_size=16)  # spe=1
        # MoE threads aux_loss functionally through state; the per-step
        # path merges the popped-empty slot into net_state
        assert _dynamic_entries(net) == [("0", [])], (
            "the per-step path's dynamic-state merge changed — update "
            "_multi_step_fn's docstring and this divergence contract: "
            f"{_dynamic_entries(net)}")

    def test_fused_path_drops_dynamic_state_params_identical(self):
        x, y = _data()
        net_a = _moe_net()
        net_a.fit(x, y, epochs=1, batch_size=16, steps_per_execution=1)
        net_b = _moe_net()
        net_b.fit(x, y, epochs=1, batch_size=16, steps_per_execution=2)
        # 1. the documented divergence: fused path carries NO dynamic
        # state (scan-carry structure is fixed at init)
        assert not _dynamic_entries(net_b), (
            "fused path now carries dynamic state — the scan-carry "
            "constraint was lifted; delete this pin and the docstring")
        # 2. the divergence is OBSERVABLE only in those keys: params and
        # init-present state must be numerically identical
        for lk in net_a.params:
            for pn in net_a.params[lk]:
                np.testing.assert_allclose(
                    np.asarray(net_a.params[lk][pn]),
                    np.asarray(net_b.params[lk][pn]),
                    rtol=2e-5, atol=2e-6,
                    err_msg=f"params {lk}/{pn} diverged between per-step "
                            f"and fused execution")
        fresh = _moe_net()
        for lk, st in fresh.net_state.items():
            for sk in st:
                np.testing.assert_allclose(
                    np.asarray(net_a.net_state[lk][sk]),
                    np.asarray(net_b.net_state[lk][sk]),
                    rtol=2e-5, atol=2e-6,
                    err_msg=f"init-present state {lk}/{sk} diverged")

    def test_dynamic_state_values_are_disposable(self):
        """The contract is only safe while dynamic slots hold DISPOSABLE
        values (per-step scratch like the popped-empty aux slot). A
        layer leaving meaningful arrays in a dynamic slot would be
        silently wrong under fusion — fail here instead."""
        net = _moe_net()
        x, y = _data()
        net.fit(x, y, epochs=1, batch_size=16)
        for lk, keys in _dynamic_entries(net):
            for sk in keys:
                v = np.asarray(net.net_state[lk][sk])
                assert v.size <= 1, (
                    f"dynamic state {lk}/{sk} holds a {v.shape} array — "
                    f"too big to be disposable scratch; the fused path "
                    f"would silently drop it (see _multi_step_fn)")
