"""Zoo smoke tests (reference `deeplearning4j-zoo/src/test/java/...
TestInstantiation.java`): instantiate each model at reduced input size,
run a forward pass and/or one training step.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    AlexNet,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    LeNet,
    ResNet50,
    SimpleCNN,
    TextGenerationLSTM,
    VGG16,
    VGG19,
)


def _img(b, h, w, c=3, seed=0):
    return np.random.default_rng(seed).standard_normal((b, h, w, c)).astype(np.float32)


def _onehot(b, n, seed=0):
    return np.eye(n, dtype=np.float32)[np.random.default_rng(seed).integers(0, n, b)]


@pytest.mark.slow   # heaviest zoo compiles; run with -m slow
def test_googlenet_builds_and_forwards():
    net = GoogLeNet(num_classes=10, height=64, width=64).init()
    out = net.output(_img(2, 64, 64))
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.slow
def test_inception_resnet_v1_builds_and_forwards():
    net = InceptionResNetV1(num_classes=8, height=80, width=80,
                            blocks35=1, blocks17=1, blocks8=1).init()
    out = net.output(_img(2, 80, 80))
    assert out.shape == (2, 8)


@pytest.mark.slow
def test_facenet_nn4_small2_trains():
    net = FaceNetNN4Small2(num_classes=6, height=64, width=64).init()
    x, y = _img(2, 64, 64), _onehot(2, 6)
    out = net.output(x)
    assert out.shape == (2, 6)
    net.fit(x, y, epochs=1, batch_size=2)
    assert np.isfinite(net.score_value)


def test_facenet_embeddings_are_l2_normalized():
    net = FaceNetNN4Small2(num_classes=6, height=64, width=64).init()
    acts, _, _, _ = net._forward_all(net.params, net.net_state,
                                     [_img(2, 64, 64)], train=False, rng=None)
    emb = np.asarray(acts["embeddings"])
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, rtol=1e-4)
