"""Persistent XLA compile cache seam (nd/compile_cache.py).

The ROADMAP names the same lever twice — fleet swap warmup pays the
full (width x bucket) program grid per successor, elastic re-formation
pays full re-jits per generation. `DL4J_COMPILE_CACHE_DIR` routes both
through jax's persistent compilation cache: the SECOND warmup of the
same configuration loads executables from disk. The cold-vs-warm
timing assert here is the seam's acceptance surface."""

import os

import pytest

import jax

from deeplearning4j_tpu.nd import compile_cache

V, D, MAXLEN, BL = 23, 16, 32, 4


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = tmp_path / "xla-cache"
    prior = jax.config.jax_compilation_cache_dir   # conftest's session cache
    monkeypatch.setenv("DL4J_COMPILE_CACHE_DIR", str(d))
    yield d
    # restore the prior destination (the suite-wide cache the conftest
    # enabled) so later tests neither read from nor write to this
    # test's tmpdir
    jax.config.update("jax_compilation_cache_dir", prior)
    compile_cache._reset_cache_instance()
    compile_cache._enabled_dir = None


class TestCompileCacheSeam:
    def test_disabled_without_env(self, monkeypatch):
        monkeypatch.delenv("DL4J_COMPILE_CACHE_DIR", raising=False)
        compile_cache._enabled_dir = None
        assert compile_cache.enable_compile_cache() is None
        assert compile_cache.compile_cache_dir() is None

    def test_enable_is_idempotent_and_creates_dir(self, cache_dir):
        got = compile_cache.enable_compile_cache()
        assert got == str(cache_dir)
        assert os.path.isdir(cache_dir)
        assert compile_cache.enable_compile_cache() == str(cache_dir)
        assert compile_cache.compile_cache_dir() == str(cache_dir)

    def test_cold_vs_warm_swap_warmup(self, cache_dir, tmp_path):
        """The fleet-swap scenario, measured the way a swap actually
        pays it — in FRESH processes (a successor starts with empty
        in-memory caches; the persistent cache is all that carries
        over): a cold child warms one server's full program grid
        (every program XLA-compiles and lands in the cache), a second
        identical child re-warms it. The warm grid must load from the
        persistent cache and come back measurably faster — plus the
        cache directory must actually hold the executables (a silent
        fallback to no-cache would still 'pass' a files-only check
        the other way around). Subprocess isolation is deliberate:
        an in-process `jax.clear_caches()` variant poisons every
        later test in the suite with mass recompiles."""
        import subprocess
        import sys

        child = (
            "import os, time\n"
            "import numpy as np\n"
            "from deeplearning4j_tpu.serving import GenerationServer\n"
            "from deeplearning4j_tpu.zoo.transformer import "
            "TransformerLM\n"
            f"net = TransformerLM(vocab_size={V}, d_model={D}, "
            f"n_layers=2, n_heads=4, max_len={MAXLEN}, seed=3).init()\n"
            "t0 = time.perf_counter()\n"
            f"GenerationServer(net, n_slots=4, n_blocks=48, "
            f"block_len={BL}, speculative=4).warmup(6, 4)\n"
            "print('ELAPSED', time.perf_counter() - t0)\n")
        env = dict(os.environ,
                   DL4J_COMPILE_CACHE_DIR=str(cache_dir),
                   JAX_PLATFORMS="cpu")

        def warmup_child():
            proc = subprocess.run(
                [sys.executable, "-c", child], env=env,
                capture_output=True, text=True, timeout=600,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            assert proc.returncode == 0, proc.stderr[-2000:]
            for line in proc.stdout.splitlines():
                if line.startswith("ELAPSED"):
                    return float(line.split()[1])
            raise AssertionError(f"no ELAPSED line: {proc.stdout!r}")

        cold = warmup_child()
        entries = [f for f in os.listdir(cache_dir)
                   if not f.endswith("-atime")]
        if not entries:
            pytest.skip("this jax backend does not populate the "
                        "persistent compilation cache on CPU")
        warm = warmup_child()
        assert warm < cold, (
            f"warm swap-warmup ({warm:.2f}s) not faster than cold "
            f"({cold:.2f}s) — persistent cache not serving the grid")
        # the committed evidence bar: a cache hit skips XLA entirely,
        # which on this grid is well over half the cold cost
        assert warm < 0.75 * cold, (cold, warm)

    def test_multihost_init_enables_seam(self, cache_dir, monkeypatch):
        """initialize_multihost routes through the seam (the elastic
        re-formation call site) — verified without bringing up a real
        distributed runtime by checking the seam state after the
        latch-guarded prologue."""
        from deeplearning4j_tpu.parallel import multihost

        compile_cache._enabled_dir = None
        # force the early-return path AFTER the seam call by marking
        # the runtime active once the cache is enabled
        calls = {}
        monkeypatch.setattr(multihost, "_enable_cpu_collectives",
                            lambda: calls.setdefault("hit", True))

        def boom(*a, **k):
            raise RuntimeError("stop before real distributed init")

        monkeypatch.setattr(multihost, "_raw_initialize", boom)
        monkeypatch.setattr(multihost, "_transient",
                            lambda e: False)
        with pytest.raises(RuntimeError, match="stop before"):
            multihost.initialize_multihost("127.0.0.1:1", 1, 0,
                                           max_attempts=1)
        assert compile_cache.compile_cache_dir() == str(cache_dir)
        assert calls.get("hit")
