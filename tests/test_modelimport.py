"""Keras import tests — golden-file style (reference
`Keras2ModelConfigurationTest.java` + per-layer tests `layers/**`):
synthetic Keras 1 & 2 .h5 files are fabricated with the C++ HDF5 writer
and imported, then outputs/weights are asserted numerically.
"""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import Hdf5Archive, KerasModelImport
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph


def write_keras_h5(path, model_config: dict, layer_weights: dict):
    """layer_weights: {layer_name: {weight_name: array}} — writes the
    Keras 2 on-disk layout (model_weights/<layer>/<layer>/<w>:0)."""
    with Hdf5Archive(path, "w") as h5:
        h5.write_attr_string("model_config", json.dumps(model_config))
        h5.write_attr_string("keras_version", "2.1.6")
        h5.write_attr_string("backend", "tensorflow")
        h5.create_group("/model_weights")
        h5.write_attr_strings("layer_names", list(layer_weights),
                              "/model_weights")
        for lname, weights in layer_weights.items():
            h5.create_group(f"/model_weights/{lname}")
            wnames = [f"{lname}/{wn}:0" for wn in weights]
            h5.write_attr_strings("weight_names", wnames,
                                  f"/model_weights/{lname}")
            h5.create_group(f"/model_weights/{lname}/{lname}")
            for wn, arr in weights.items():
                h5.write_dataset(f"/model_weights/{lname}/{lname}/{wn}:0",
                                 np.asarray(arr, np.float32))


def dense_cfg(name, units, activation, input_shape=None, keras1=False):
    cfg = {"name": name, "activation": activation, "use_bias": True}
    if keras1:
        cfg["output_dim"] = units
    else:
        cfg["units"] = units
    if input_shape is not None:
        cfg["batch_input_shape"] = [None] + list(input_shape)
    return {"class_name": "Dense", "config": cfg}


class TestHdf5Archive:
    def test_roundtrip(self, tmp_path):
        p = tmp_path / "t.h5"
        with Hdf5Archive(p, "w") as h5:
            h5.write_attr_string("model_config", '{"x": 1}')
            h5.create_group("/g")
            h5.write_attr_strings("names", ["a", "b"], "/g")
            h5.write_dataset("/g/data", np.arange(6, np.float32).reshape(2, 3)
                             if False else np.arange(6, dtype=np.float32).reshape(2, 3))
        with Hdf5Archive(p) as h5:
            assert h5.read_attr_string("model_config") == '{"x": 1}'
            assert h5.read_attr_strings("names", "/g") == ["a", "b"]
            np.testing.assert_array_equal(
                h5.read_dataset("/g/data"),
                np.arange(6, dtype=np.float32).reshape(2, 3))
            assert h5.exists("/g/data") and not h5.exists("/nope")


class TestSequentialImport:
    def test_mlp_forward_matches_manual(self, tmp_path):
        rng = np.random.default_rng(0)
        W1 = rng.standard_normal((8, 16)).astype(np.float32)
        b1 = rng.standard_normal(16).astype(np.float32)
        W2 = rng.standard_normal((16, 4)).astype(np.float32)
        b2 = rng.standard_normal(4).astype(np.float32)
        config = {"class_name": "Sequential", "config": [
            dense_cfg("dense_1", 16, "relu", input_shape=[8]),
            dense_cfg("dense_2", 4, "softmax"),
        ]}
        p = tmp_path / "mlp.h5"
        write_keras_h5(p, config, {
            "dense_1": {"kernel": W1, "bias": b1},
            "dense_2": {"kernel": W2, "bias": b2},
        })
        net = KerasModelImport.import_keras_model_and_weights(p)
        assert isinstance(net, MultiLayerNetwork)
        x = rng.standard_normal((5, 8)).astype(np.float32)
        got = np.asarray(net.output(x))
        h = np.maximum(x @ W1 + b1, 0.0)
        logits = h @ W2 + b2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        want = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_keras1_dialect(self, tmp_path):
        # Keras 1 config fields AND Keras 1 weight names ("<layer>_W")
        rng = np.random.default_rng(1)
        W = rng.standard_normal((6, 3)).astype(np.float32)
        b = np.zeros(3, np.float32)
        config = {"class_name": "Sequential", "config": [
            dense_cfg("d", 3, "sigmoid", input_shape=[6], keras1=True),
        ]}
        p = tmp_path / "k1.h5"
        write_keras_h5(p, config, {"d": {"d_W": W, "d_b": b}})
        net = KerasModelImport.import_keras_model_and_weights(p)
        np.testing.assert_array_equal(np.asarray(net.params["0"]["W"]), W)

    def test_unmatched_weight_names_raise(self, tmp_path):
        config = {"class_name": "Sequential", "config": [
            dense_cfg("d", 3, "sigmoid", input_shape=[6]),
        ]}
        p = tmp_path / "bad.h5"
        write_keras_h5(p, config, {"d": {"mystery": np.zeros((6, 3), np.float32)}})
        with pytest.raises(ValueError, match="could not match"):
            KerasModelImport.import_keras_model_and_weights(p)

    def test_cnn_with_flatten(self, tmp_path):
        rng = np.random.default_rng(2)
        K = rng.standard_normal((3, 3, 1, 4)).astype(np.float32) * 0.1
        bK = np.zeros(4, np.float32)
        W = rng.standard_normal((4 * 4 * 4, 2)).astype(np.float32) * 0.1
        b = np.zeros(2, np.float32)
        config = {"class_name": "Sequential", "config": [
            {"class_name": "Conv2D", "config": {
                "name": "conv", "filters": 4, "kernel_size": [3, 3],
                "strides": [1, 1], "padding": "same", "activation": "relu",
                "use_bias": True, "batch_input_shape": [None, 8, 8, 1]}},
            {"class_name": "MaxPooling2D", "config": {
                "name": "pool", "pool_size": [2, 2], "strides": [2, 2],
                "padding": "valid"}},
            {"class_name": "Flatten", "config": {"name": "flat"}},
            dense_cfg("out", 2, "softmax"),
        ]}
        p = tmp_path / "cnn.h5"
        write_keras_h5(p, config, {
            "conv": {"kernel": K, "bias": bK},
            "out": {"kernel": W, "bias": b},
        })
        net = KerasModelImport.import_keras_model_and_weights(p)
        np.testing.assert_array_equal(np.asarray(net.params["0"]["W"]), K)
        x = rng.standard_normal((2, 8, 8, 1)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 2)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)

    def test_lstm_gate_reorder(self, tmp_path):
        H, F = 3, 2
        # blocks tagged by constant value: i=1, f=2, c=3, o=4
        K = np.concatenate([np.full((F, H), v, np.float32) for v in (1, 2, 3, 4)], 1)
        R = np.concatenate([np.full((H, H), v, np.float32) for v in (1, 2, 3, 4)], 1)
        b = np.concatenate([np.full((H,), v, np.float32) for v in (1, 2, 3, 4)])
        config = {"class_name": "Sequential", "config": [
            {"class_name": "LSTM", "config": {
                "name": "lstm", "units": H, "activation": "tanh",
                "recurrent_activation": "sigmoid", "return_sequences": False,
                "batch_input_shape": [None, 5, F]}},
            dense_cfg("out", 2, "softmax"),
        ]}
        p = tmp_path / "lstm.h5"
        rng = np.random.default_rng(3)
        write_keras_h5(p, config, {
            "lstm": {"kernel": K, "recurrent_kernel": R, "bias": b},
            "out": {"kernel": rng.standard_normal((H, 2)).astype(np.float32),
                    "bias": np.zeros(2, np.float32)},
        })
        net = KerasModelImport.import_keras_model_and_weights(p)
        W = np.asarray(net.params["0"]["W"])
        # our IFOG order: blocks must read i=1, f=2, o=4, g(c)=3
        assert W[0, 0] == 1 and W[0, H] == 2 and W[0, 2 * H] == 4 and W[0, 3 * H] == 3
        # LastTimeStep inserted for return_sequences=False
        x = rng.standard_normal((2, 5, F)).astype(np.float32)
        assert np.asarray(net.output(x)).shape == (2, 2)

    def test_batchnorm_state(self, tmp_path):
        F = 4
        gamma = np.full(F, 1.5, np.float32)
        beta = np.full(F, -0.5, np.float32)
        mean = np.full(F, 2.0, np.float32)
        var = np.full(F, 4.0, np.float32)
        config = {"class_name": "Sequential", "config": [
            {"class_name": "BatchNormalization", "config": {
                "name": "bn", "epsilon": 1e-3, "momentum": 0.99,
                "batch_input_shape": [None, F]}},
        ]}
        p = tmp_path / "bn.h5"
        write_keras_h5(p, config, {"bn": {
            "gamma": gamma, "beta": beta, "moving_mean": mean,
            "moving_variance": var}})
        net = KerasModelImport.import_keras_model_and_weights(p)
        x = np.random.default_rng(4).standard_normal((6, F)).astype(np.float32)
        got = np.asarray(net.output(x))
        want = gamma * (x - mean) / np.sqrt(var + 1e-3) + beta
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


class TestFunctionalImport:
    def test_two_branch_model(self, tmp_path):
        rng = np.random.default_rng(5)
        W1 = rng.standard_normal((6, 8)).astype(np.float32)
        W2 = rng.standard_normal((6, 8)).astype(np.float32)
        W3 = rng.standard_normal((16, 3)).astype(np.float32)
        config = {"class_name": "Model", "config": {
            "name": "branchy",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 6]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "a",
                 "config": {"name": "a", "units": 8, "activation": "relu",
                            "use_bias": True},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "b",
                 "config": {"name": "b", "units": 8, "activation": "relu",
                            "use_bias": True},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Concatenate", "name": "merge",
                 "config": {"name": "merge"},
                 "inbound_nodes": [[["a", 0, 0, {}], ["b", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 3,
                            "activation": "softmax", "use_bias": True},
                 "inbound_nodes": [[["merge", 0, 0, {}]]]},
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        }}
        p = tmp_path / "func.h5"
        write_keras_h5(p, config, {
            "a": {"kernel": W1, "bias": np.zeros(8, np.float32)},
            "b": {"kernel": W2, "bias": np.zeros(8, np.float32)},
            "out": {"kernel": W3, "bias": np.zeros(3, np.float32)},
        })
        net = KerasModelImport.import_keras_model_and_weights(p)
        assert isinstance(net, ComputationGraph)
        x = rng.standard_normal((4, 6)).astype(np.float32)
        got = np.asarray(net.output(x))
        ha = np.maximum(x @ W1, 0)
        hb = np.maximum(x @ W2, 0)
        logits = np.concatenate([ha, hb], 1) @ W3
        e = np.exp(logits - logits.max(1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(1, keepdims=True),
                                   rtol=1e-4, atol=1e-5)
