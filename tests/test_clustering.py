"""Nearest-neighbors / clustering / t-SNE tests (reference strategy:
VPTree/KDTree correctness vs brute force, k-means convergence, t-SNE
cluster preservation)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree,
    KMeansClustering,
    QuadTree,
    SpTree,
    VPTree,
)
from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient,
    NearestNeighborsServer,
)
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def brute_knn(points, query, k):
    d = np.sqrt(np.sum((points - query[None, :]) ** 2, axis=1))
    order = np.argsort(d)
    return list(order[:k]), list(d[order[:k]])


@pytest.fixture(scope="module")
def points():
    return np.random.default_rng(0).standard_normal((300, 8))


class TestVPTree:
    def test_matches_brute_force(self, points):
        tree = VPTree(points)
        for qi in (0, 7, 123):
            q = points[qi] + 0.01
            got_i, got_d = tree.knn(q, 10)
            want_i, want_d = brute_knn(points, q, 10)
            assert got_i == want_i
            np.testing.assert_allclose(got_d, want_d, rtol=1e-9)

    def test_cosine_distance(self, points):
        tree = VPTree(points, distance="cosine")
        q = points[5]
        got_i, _ = tree.knn(q, 1)
        assert got_i[0] == 5


class TestKDTree:
    def test_matches_brute_force(self, points):
        tree = KDTree(points)
        q = np.random.default_rng(1).standard_normal(8)
        got_i, got_d = tree.knn(q, 15)
        want_i, want_d = brute_knn(points, q, 15)
        assert got_i == want_i

    def test_range_query(self):
        pts = np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0], [0.5, 0.6]])
        tree = KDTree(pts)
        inside = tree.range([0.0, 0.0], [1.0, 1.0])
        assert sorted(inside) == [0, 1, 3]


class TestTrees:
    def test_quadtree_mass_conservation(self):
        pts = np.random.default_rng(2).standard_normal((100, 2))
        tree = QuadTree.build(pts)
        assert tree.size == 100
        np.testing.assert_allclose(tree.com, pts.mean(axis=0), atol=1e-9)

    def test_sptree_matches_exact_forces_at_theta0(self):
        pts = np.random.default_rng(3).standard_normal((50, 3))
        tree = SpTree.build(pts)
        assert tree.size == 50
        i = 7
        neg = np.zeros(3)
        z = tree.compute_non_edge_forces(pts[i], 0.0, neg)  # theta=0 → exact
        diff = pts[i] - np.delete(pts, i, axis=0)
        q = 1.0 / (1.0 + np.sum(diff ** 2, axis=1))
        np.testing.assert_allclose(z, q.sum(), rtol=1e-6)
        np.testing.assert_allclose(neg, (q ** 2)[:, None] * diff, atol=1e-6,
                                   rtol=1e-5) if False else \
            np.testing.assert_allclose(neg, ((q ** 2)[:, None] * diff).sum(0),
                                       rtol=1e-6)


class TestKMeans:
    def test_recovers_separated_clusters(self):
        rng = np.random.default_rng(4)
        c1 = rng.standard_normal((80, 4)) * 0.2 + 5
        c2 = rng.standard_normal((80, 4)) * 0.2 - 5
        c3 = rng.standard_normal((80, 4)) * 0.2
        pts = np.concatenate([c1, c2, c3])
        cs = KMeansClustering(k=3, max_iterations=50).apply_to(pts)
        labels = cs.assignments
        # every true cluster is one predicted cluster
        for block in (labels[:80], labels[80:160], labels[160:]):
            assert len(set(block.tolist())) == 1
        assert len({labels[0], labels[80], labels[160]}) == 3
        assert cs.nearest_cluster(np.full(4, 5.0)) == labels[0]

    def test_cluster_set_api(self):
        pts = np.random.default_rng(5).standard_normal((30, 2))
        cs = KMeansClustering(k=4).apply_to(pts)
        clusters = cs.get_clusters()
        assert len(clusters) == 4
        assert sum(len(c.points) for c in clusters) == 30


class TestTsne:
    def _clustered(self, n=60, d=10):
        rng = np.random.default_rng(6)
        a = rng.standard_normal((n, d)) * 0.3 + 4
        b = rng.standard_normal((n, d)) * 0.3 - 4
        return np.concatenate([a, b])

    def test_exact_separates_clusters(self):
        x = self._clustered()
        y = Tsne(perplexity=15.0, n_iter=250, seed=0).fit_transform(x)
        assert y.shape == (120, 2)
        ca, cb = y[:60].mean(0), y[60:].mean(0)
        spread = max(y[:60].std(), y[60:].std())
        assert np.linalg.norm(ca - cb) > 2 * spread

    @pytest.mark.slow   # large-N smoke; exactness tests stay default
    def test_barnes_hut_runs_large(self):
        rng = np.random.default_rng(7)
        x = np.concatenate([rng.standard_normal((300, 5)) + 3,
                            rng.standard_normal((300, 5)) - 3])
        y = BarnesHutTsne(theta=0.8, n_iter=60, seed=0).fit_transform(x)
        assert y.shape == (600, 2)
        assert np.all(np.isfinite(y))
        ca, cb = y[:300].mean(0), y[300:].mean(0)
        assert np.linalg.norm(ca - cb) > 1e-2


class TestServer:
    def test_rest_roundtrip(self, points):
        server = NearestNeighborsServer(points).start()
        try:
            client = NearestNeighborsClient(f"http://127.0.0.1:{server.port}")
            res = client.knn(index=3, k=5)
            assert res["results"][0]["index"] == 3
            q = points[10] + 0.001
            res2 = client.knn_new(q.astype(np.float32), 4)
            want_i, _ = brute_knn(points, q, 4)
            assert [r["index"] for r in res2["results"]] == want_i
        finally:
            server.stop()
