"""Multi-process (multi-host) distributed training proof.

The reference proves its distributed path in-process on every CI run
(`BaseSparkTest.java:89`, Spark `local[N]`). Here: 2 OS processes
around a `jax.distributed` coordinator, each with 2 virtual CPU
devices, running the global-view ParallelTrainer sync program over the
4-device global mesh — asserted loss-identical to a single-process run
on the same mesh (see `parallel/multihost_smoke.py`).
"""

from deeplearning4j_tpu.parallel.multihost_smoke import run_smoke


class TestMultiProcessDistributed:
    def test_two_process_sync_matches_single_process(self):
        report = run_smoke(n=2)
        assert report["match"]
        assert report["n_processes"] == 2
        # the trajectory must show learning, not just agreement
        assert report["losses"][-1] < report["losses"][0] * 0.7
        # per-process eval + JSON transport + merge == full-data eval
        assert report["eval_merge_match"]
