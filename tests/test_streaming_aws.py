"""Streaming + cloud adapter tests (local transports / injected fetch)."""

import io

import numpy as np
import pytest

from deeplearning4j_tpu.aws import S3DataSetIterator
from deeplearning4j_tpu.streaming import (
    LocalQueueTransport,
    NDArrayConsumer,
    NDArrayPublisher,
    csv_to_dataset,
    deserialize_ndarray,
    serialize_ndarray,
)


class TestNDArrayWire:
    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32, np.int64])
    def test_roundtrip(self, dtype):
        arr = (np.random.default_rng(0).standard_normal((3, 4, 5)) * 10).astype(dtype)
        back = deserialize_ndarray(serialize_ndarray(arr))
        np.testing.assert_array_equal(arr, back)
        assert back.dtype == dtype

    def test_pub_sub(self):
        transport = LocalQueueTransport()
        pub = NDArrayPublisher(transport, "grads")
        sub = NDArrayConsumer(transport, "grads")
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        pub.publish(arr)
        np.testing.assert_array_equal(sub.consume(timeout=1), arr)

    def test_kafka_gated(self):
        from deeplearning4j_tpu.streaming import KafkaTransport
        with pytest.raises(ImportError, match="kafka"):
            KafkaTransport("localhost:9092")


def test_csv_to_dataset():
    ds = csv_to_dataset(["1,2,0", "3,4,1"], num_classes=2)
    np.testing.assert_array_equal(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_array_equal(ds.labels, [[1, 0], [0, 1]])


class TestS3:
    def test_iterator_with_injected_fetch(self):
        blobs = {}
        for i in range(2):
            buf = io.BytesIO()
            np.savez(buf, features=np.full((4, 3), i, np.float32),
                     labels=np.eye(2, dtype=np.float32)[[i % 2] * 4])
            blobs[f"part{i}.npz"] = buf.getvalue()
        it = S3DataSetIterator(sorted(blobs), blobs.__getitem__)
        out = list(it)
        assert len(out) == 2
        assert out[1].features[0, 0] == 1.0

    def test_uploader_gated_without_boto3(self):
        from deeplearning4j_tpu.aws import S3Uploader
        with pytest.raises(ImportError, match="boto3"):
            S3Uploader("bucket")


class TestKafkaTransportWithFakeBroker:
    """Integration-tests KafkaTransport's send/flush/poll logic against a
    faithful in-memory fake of the kafka-python API (no real broker in
    this image; the fake preserves the client call contract —
    send(topic, bytes) -> flush, poll(timeout_ms, max_records) ->
    {tp: [records]})."""

    def _install_fake_kafka(self, monkeypatch):
        import sys
        import types
        from collections import defaultdict

        broker = defaultdict(list)          # topic -> [bytes]
        offsets = defaultdict(int)          # topic -> consumer offset

        class FakeProducer:
            def __init__(self, bootstrap_servers=None):
                self.bootstrap = bootstrap_servers
                self._pending = []

            def send(self, topic, value):
                self._pending.append((topic, value))

            def flush(self):
                for topic, value in self._pending:
                    broker[topic].append(value)
                self._pending = []

        class _Record:
            def __init__(self, value):
                self.value = value

        class FakeConsumer:
            def __init__(self, topic, bootstrap_servers=None,
                         auto_offset_reset="earliest"):
                assert auto_offset_reset == "earliest"
                self.topic = topic

            def poll(self, timeout_ms=0, max_records=1):
                t = self.topic
                out = {}
                avail = broker[t][offsets[t]:offsets[t] + max_records]
                if avail:
                    offsets[t] += len(avail)
                    out[(t, 0)] = [_Record(v) for v in avail]
                return out

        fake = types.ModuleType("kafka")
        fake.KafkaProducer = FakeProducer
        fake.KafkaConsumer = FakeConsumer
        monkeypatch.setitem(sys.modules, "kafka", fake)
        return broker

    def test_ndarray_roundtrip_over_kafka_contract(self, monkeypatch):
        broker = self._install_fake_kafka(monkeypatch)
        from deeplearning4j_tpu.streaming.ndarray import (
            KafkaTransport, NDArrayConsumer, NDArrayPublisher)

        tr = KafkaTransport("broker:9092")
        pub = NDArrayPublisher(tr, "arrays")
        sub = NDArrayConsumer(tr, "arrays")
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        pub.publish(a)
        assert len(broker["arrays"]) == 1    # flushed to the broker
        b = sub.consume(timeout=0.1)
        np.testing.assert_array_equal(a, b)

    def test_timeout_when_topic_empty(self, monkeypatch):
        self._install_fake_kafka(monkeypatch)
        from deeplearning4j_tpu.streaming.ndarray import KafkaTransport
        tr = KafkaTransport("broker:9092")
        with pytest.raises(TimeoutError):
            tr.receive("empty-topic", timeout=0.05)

    def test_serving_route_over_kafka_contract(self, monkeypatch):
        self._install_fake_kafka(monkeypatch)
        from deeplearning4j_tpu.streaming.ndarray import (
            KafkaTransport, NDArrayConsumer, NDArrayPublisher)
        from deeplearning4j_tpu.streaming.routes import ServingRoute
        from tests.test_util_streaming_depth import _trained_xor_net

        net, x = _trained_xor_net()
        tr = KafkaTransport("broker:9092")
        route = ServingRoute(tr, "in", "out", model=net)
        NDArrayPublisher(tr, "in").publish(x)
        assert route.run(max_messages=1, timeout=0.1) == 1
        out = NDArrayConsumer(tr, "out").consume(timeout=0.5)
        assert out.shape == (4, 2)
