"""Streaming + cloud adapter tests (local transports / injected fetch)."""

import io

import numpy as np
import pytest

from deeplearning4j_tpu.aws import S3DataSetIterator
from deeplearning4j_tpu.streaming import (
    LocalQueueTransport,
    NDArrayConsumer,
    NDArrayPublisher,
    csv_to_dataset,
    deserialize_ndarray,
    serialize_ndarray,
)


class TestNDArrayWire:
    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32, np.int64])
    def test_roundtrip(self, dtype):
        arr = (np.random.default_rng(0).standard_normal((3, 4, 5)) * 10).astype(dtype)
        back = deserialize_ndarray(serialize_ndarray(arr))
        np.testing.assert_array_equal(arr, back)
        assert back.dtype == dtype

    def test_pub_sub(self):
        transport = LocalQueueTransport()
        pub = NDArrayPublisher(transport, "grads")
        sub = NDArrayConsumer(transport, "grads")
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        pub.publish(arr)
        np.testing.assert_array_equal(sub.consume(timeout=1), arr)

    def test_kafka_gated(self):
        from deeplearning4j_tpu.streaming import KafkaTransport
        with pytest.raises(ImportError, match="kafka"):
            KafkaTransport("localhost:9092")


def test_csv_to_dataset():
    ds = csv_to_dataset(["1,2,0", "3,4,1"], num_classes=2)
    np.testing.assert_array_equal(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_array_equal(ds.labels, [[1, 0], [0, 1]])


class TestS3:
    def test_iterator_with_injected_fetch(self):
        blobs = {}
        for i in range(2):
            buf = io.BytesIO()
            np.savez(buf, features=np.full((4, 3), i, np.float32),
                     labels=np.eye(2, dtype=np.float32)[[i % 2] * 4])
            blobs[f"part{i}.npz"] = buf.getvalue()
        it = S3DataSetIterator(sorted(blobs), blobs.__getitem__)
        out = list(it)
        assert len(out) == 2
        assert out[1].features[0, 0] == 1.0

    def test_uploader_gated_without_boto3(self):
        from deeplearning4j_tpu.aws import S3Uploader
        with pytest.raises(ImportError, match="boto3"):
            S3Uploader("bucket")
