"""Mixed-precision dtype policy (nd/dtype.py) — policy seams,
resolution order, serde, and the bf16-vs-fp32 training contract:
bf16 compute with an fp32 master copy (params + updater state stay
fp32, gradients arrive bf16, losses stay fp32), loss trajectories
within the documented tolerance of pure fp32 (docs/PRECISION.md).
Device-free (CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nd import dtype as dt
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.common.updaters import Adam


def build(policy=None, depth=4, seed=7):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
    if policy is not None:
        b = b.dtype_policy(policy)
    b = b.list()
    for _ in range(depth):
        b = b.layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
    conf = (b.layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def make_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 16)).astype(np.float32)
    w = rng.standard_normal((16, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


# ------------------------------------------------------------ policy seams
class TestPolicySeams:
    def test_presets_and_names(self):
        p = dt.mixed_bf16()
        assert p.is_mixed and p.name == "mixed_bf16"
        assert jnp.dtype(p.param_dtype) == jnp.float32
        assert jnp.dtype(p.compute_dtype) == jnp.bfloat16
        assert not dt.DataTypePolicy().is_mixed
        assert dt.policy_from_name("float32").name == "float32"
        assert dt.policy_from_name("bf16").name == "mixed_bf16"
        with pytest.raises(ValueError):
            dt.policy_from_name("fp8")

    def test_get_default_policy_sees_the_active_policy(self):
        # the legacy get_default_dtype() only exposed param_dtype —
        # callers could not see an active mixed policy
        try:
            dt.set_default_dtype(compute_dtype=jnp.bfloat16)
            assert dt.get_default_policy().is_mixed
            assert dt.get_default_dtype() == jnp.float32
        finally:
            dt.set_default_dtype(reset=True)
        assert not dt.get_default_policy().is_mixed

    def test_set_default_dtype_explicit_reset(self):
        dt.set_default_dtype(compute_dtype=jnp.bfloat16)
        # reset=True restores factory FIRST, then applies overrides
        out = dt.set_default_dtype(reset=True)
        assert not out.is_mixed
        dt.set_default_policy(dt.mixed_bf16())
        assert dt.get_default_policy().is_mixed
        dt.set_default_policy(None)
        assert not dt.get_default_policy().is_mixed

    def test_non_floating_inputs_pass_unchanged(self):
        p = dt.mixed_bf16()
        ids = jnp.arange(400, dtype=jnp.int32)       # > bf16's 256 span
        out = p.cast_compute(ids)
        assert out is ids
        b = jnp.array([True, False])
        assert p.cast_compute(b) is b
        f = jnp.ones((3,), jnp.float32)
        assert p.cast_compute(f).dtype == jnp.bfloat16

    def test_cast_params_identity_for_fp32(self):
        p = dt.DataTypePolicy()
        tree = {"0": {"W": jnp.ones((2, 2))}}
        assert p.cast_params(tree) is tree          # no retrace churn

    def test_serde_roundtrip(self):
        p = dt.mixed_bf16()
        assert dt.DataTypePolicy.from_dict(p.to_dict()) == p
        assert dt.as_policy("mixed_bf16") == p
        assert dt.as_policy(p.to_dict()) == p
        assert dt.as_policy(None) is None


class TestResolution:
    def test_order_env_beats_arg_beats_conf(self, monkeypatch):
        conf = build("mixed_bf16").conf
        assert dt.resolve_policy(None, conf).is_mixed
        # explicit arg beats conf
        assert not dt.resolve_policy("float32", conf).is_mixed
        # env beats everything (mirrors DL4J_SCAN_LAYERS)
        monkeypatch.setenv("DL4J_DTYPE_POLICY", "0")
        assert not dt.resolve_policy("mixed_bf16", conf).is_mixed
        monkeypatch.setenv("DL4J_DTYPE_POLICY", "mixed_bf16")
        assert dt.resolve_policy("float32", conf).is_mixed
        monkeypatch.setenv("DL4J_DTYPE_POLICY", "float999")
        with pytest.raises(ValueError):
            dt.resolve_policy(None, conf)

    def test_env_ab_toggle_on_container(self, monkeypatch):
        monkeypatch.setenv("DL4J_DTYPE_POLICY", "mixed_bf16")
        net = build()                                 # no conf policy
        assert net.dtype.is_mixed
        monkeypatch.delenv("DL4J_DTYPE_POLICY")
        assert not build().dtype.is_mixed

    def test_conf_serde_carries_policy(self):
        net = build("mixed_bf16")
        conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert MultiLayerNetwork(conf2).dtype.is_mixed
        # absent field stays None → process default
        net3 = build()
        assert net3.conf.dtype_policy is None
        conf4 = MultiLayerConfiguration.from_json(net3.conf.to_json())
        assert conf4.dtype_policy is None

    def test_graph_builder_and_serde(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=8, n_out=8), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=3), "d")
                .set_outputs("out")
                .dtype_policy("mixed_bf16")
                .build())
        assert ComputationGraph(conf).dtype.is_mixed
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert ComputationGraph(conf2).dtype.is_mixed


# --------------------------------------------------------- mixed training
class TestMixedTraining:
    def test_master_stays_fp32_and_grads_are_bf16(self):
        net = build("mixed_bf16")
        x, y = make_data()
        seen = []
        orig = net._apply_updates

        def spy(params, grads, upd, step):
            seen.append(jax.tree_util.tree_map(lambda g: g.dtype, grads))
            return orig(params, grads, upd, step)

        net._apply_updates = spy
        net.fit(x, y, epochs=1, batch_size=16, shuffle=False)
        # grads arrive in compute dtype (the wire dtype of a DP
        # all-reduce)...
        assert all(d == jnp.bfloat16
                   for d in jax.tree_util.tree_leaves(seen[0]))
        # ...while the master copy stays fp32
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(net.updater_state):
            assert leaf.dtype == jnp.float32

    def test_trajectory_within_tolerance_of_fp32(self):
        # the documented band (docs/PRECISION.md): after 12 steps on a
        # separable problem, |loss_bf16 − loss_fp32| ≤ 5% of the
        # initial loss, and both must actually learn
        x, y = make_data()
        fp = build()
        bf = build("mixed_bf16")
        init = float(fp.score_value) if fp.score_value == fp.score_value \
            else None
        from deeplearning4j_tpu.datasets.dataset import DataSet
        ds = DataSet(x, y)
        init = float(build().score(ds))
        fp.fit(x, y, epochs=3, batch_size=16, shuffle=False)
        bf.fit(x, y, epochs=3, batch_size=16, shuffle=False)
        d, b = float(fp.score(ds)), float(bf.score(ds))
        assert d < 0.8 * init and b < 0.8 * init
        assert abs(d - b) <= 0.05 * init, (init, d, b)

    def test_fused_multi_step_matches_per_step(self):
        x, y = make_data()
        a = build("mixed_bf16")
        a.fit(x, y, epochs=2, batch_size=16, shuffle=False)
        b = build("mixed_bf16")
        b.fit(x, y, epochs=2, batch_size=16, shuffle=False,
              steps_per_execution=4)
        for p, q in zip(jax.tree_util.tree_leaves(a.params),
                        jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(p), np.asarray(q),
                                       rtol=2e-5, atol=2e-5)

    def test_output_and_loss_stay_fp32(self):
        net = build("mixed_bf16")
        x, y = make_data(16)
        out = net.output(x)
        assert out.dtype == jnp.float32
        from deeplearning4j_tpu.datasets.dataset import DataSet
        assert np.isfinite(net.score(DataSet(x, y)))

    def test_embedding_ids_survive_mixed_policy(self):
        # float-carried token ids above 256 would be corrupted by a
        # bf16 input cast — they must reach the embedding uncast
        from deeplearning4j_tpu.nn.layers import EmbeddingLayer
        b = (NeuralNetConfiguration.builder().seed(3)
             .dtype_policy("mixed_bf16").list()
             .layer(EmbeddingLayer(n_in=512, n_out=8))
             .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                                loss="mcxent")))
        conf = b.set_input_type(InputType.recurrent(512)).build()
        net = MultiLayerNetwork(conf).init()
        ids = jnp.asarray([[300, 301], [511, 2]], jnp.float32)
        out_hi = np.asarray(net.output(ids))
        # neighbouring ids must produce DIFFERENT embeddings (a bf16
        # round would collapse 300 and 301 onto the same row)
        assert not np.allclose(out_hi[0, 0], out_hi[0, 1])

    def test_frozen_embedding_ids_survive_mixed_policy(self):
        # transfer-learning pattern: a FrozenLayer-wrapped embedding
        # must still be recognized as an id consumer (the guard
        # unwraps wrappers — nn/scan_stack.consumes_token_ids)
        from deeplearning4j_tpu.nn.layers import EmbeddingLayer
        from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
        from deeplearning4j_tpu.nn import scan_stack
        emb = EmbeddingLayer(n_in=512, n_out=8)
        assert scan_stack.consumes_token_ids(emb)
        assert scan_stack.consumes_token_ids(FrozenLayer(layer=emb))
        assert not scan_stack.consumes_token_ids(
            DenseLayer(n_in=8, n_out=8))

    def test_graph_container_mixed_trains(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        conf = (ComputationGraphConfiguration.graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=16, n_out=16,
                                            activation="tanh",
                                            updater=Adam(0.01)), "in")
                .add_layer("out", OutputLayer(n_in=16, n_out=4,
                                              activation="softmax",
                                              loss="mcxent",
                                              updater=Adam(0.01)), "d1")
                .set_outputs("out")
                .dtype_policy("mixed_bf16")
                .build())
        net = ComputationGraph(conf).init()
        x, y = make_data()
        net.fit(x, y, epochs=2, batch_size=16)
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32
        from deeplearning4j_tpu.datasets.dataset import DataSet
        assert np.isfinite(net.score(DataSet(x, y)))


# ------------------------------------------------------- wire accounting
class TestWireDtypes:
    def test_exchange_wire_bytes_grad_dtype(self):
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        params = {"0": {"W": jnp.zeros((8, 8)), "b": jnp.zeros((8,))}}
        dense32 = gs.exchange_wire_bytes(params, "dense")
        dense16 = gs.exchange_wire_bytes(params, "dense",
                                         grad_dtype=jnp.bfloat16)
        assert dense16 == dense32 / 2
        # threshold wire is int8 regardless of the grad dtype
        t = gs.exchange_wire_bytes(params, "threshold", n_workers=4)
        assert t == 72 * 1 + 8.0

    def test_exchange_jaxpr_dense_carries_real_dtype(self):
        from benchtools.hlo_cost import collective_table
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        params = {"0": {"W": jnp.zeros((16, 16))}}
        j32 = gs.exchange_jaxpr(params, "dense", 4)
        j16 = gs.exchange_jaxpr(params, "dense", 4,
                                grad_dtype=jnp.bfloat16)
        b32 = collective_table(j32)["comm_bytes_per_step"]
        b16 = collective_table(j16)["comm_bytes_per_step"]
        assert b16 == b32 / 2

    def test_trainer_mixed_threshold_parity(self):
        # end-to-end: mixed-precision threshold gradient sharing on the
        # default (bucketed) path — bf16 grads upcast before the EF
        # encode; the trajectory stays in the dense band and the
        # residual/master state stay fp32
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        from deeplearning4j_tpu.datasets.dataset import DataSet
        x, y = make_data(320)
        ds = DataSet(x, y)
        init = float(build().score(ds))
        # 50 sync steps at B=32 — the verify.sh gradient-sharing
        # smoke's regime, where the error-feedback band is calibrated
        dense = build("mixed_bf16")
        ParallelTrainer(dense, device_mesh(), mode="sync").fit(
            x, y, epochs=5, batch_size=32)
        thr = build("mixed_bf16")
        tr = ParallelTrainer(thr, device_mesh(), mode="sync",
                             gradient_sharing="threshold")
        tr.fit(x, y, epochs=5, batch_size=32)
        d, t = float(dense.score(ds)), float(thr.score(ds))
        assert d < 0.8 * init and t < 0.8 * init
        assert abs(t - d) <= 0.35 * init, (init, d, t)
        for leaf in jax.tree_util.tree_leaves(thr.params):
            assert leaf.dtype == jnp.float32
