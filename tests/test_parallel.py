"""SPMD parallelism tests on the virtual 8-device CPU mesh — the
reference tested distributed logic in-process the same way
(Spark local[N], SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.common.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import MeshSpec, ParallelInference, ParallelTrainer, make_mesh
from deeplearning4j_tpu.parallel.mesh import device_mesh


def mlp_conf(updater=None, seed=42):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(0.02)).list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax", loss="mcxent"))
            .build())


class TestMesh:
    def test_eight_virtual_devices(self):
        assert len(jax.devices()) == 8

    def test_make_mesh(self):
        mesh = make_mesh(MeshSpec.of(data=4, model=2))
        assert mesh.shape == {"data": 4, "model": 2}
        mesh2 = device_mesh()
        assert mesh2.shape["data"] == 8

    def test_mesh_spec_serde(self):
        spec = MeshSpec.of(data=2, model=4)
        assert MeshSpec.from_dict(spec.to_dict()) == spec


class TestParallelTrainer:
    def test_sync_mode_learns_iris(self):
        x, y = load_iris()
        net = MultiLayerNetwork(mlp_conf()).init()
        trainer = ParallelTrainer(net, device_mesh(), mode="sync")
        trainer.fit(x[:144], y[:144], epochs=20, batch_size=48)
        e = net.evaluate(ArrayDataSetIterator(x, y, batch_size=150))
        assert e.accuracy() > 0.9, e.stats()

    def test_sync_matches_single_device(self):
        """Data-sharded sync training must equal single-device training
        bit-for-bit up to float assoc (the psum is a mean over the same
        global batch) — the parity test the reference ran between
        cuDNN and built-in paths (ValidateCudnnLSTM style)."""
        x, y = load_iris()
        x, y = x[:96], y[:96]
        net1 = MultiLayerNetwork(mlp_conf(updater=Sgd(0.05))).init()
        net1.fit(x, y, epochs=3, batch_size=48, shuffle=False)

        net2 = MultiLayerNetwork(mlp_conf(updater=Sgd(0.05))).init()
        trainer = ParallelTrainer(net2, device_mesh(), mode="sync")
        trainer.fit(ArrayDataSetIterator(x, y, batch_size=48, shuffle=False), epochs=3)

        for k in net1.param_table():
            np.testing.assert_allclose(np.asarray(net1.param_table()[k]),
                                       np.asarray(net2.param_table()[k]),
                                       atol=2e-5,
                                       err_msg=f"param {k} diverged")

    def test_sync_fused_drain_matches_per_step(self):
        """`steps_per_execution > 1` must be numerics-identical to the
        per-step sync path (same rng fold per iteration, same psum) —
        only the dispatch granularity changes."""
        x, y = load_iris()
        x, y = x[:96], y[:96]
        net1 = MultiLayerNetwork(mlp_conf(updater=Sgd(0.05))).init()
        ParallelTrainer(net1, device_mesh(), mode="sync").fit(
            ArrayDataSetIterator(x, y, batch_size=24, shuffle=False),
            epochs=2)

        net2 = MultiLayerNetwork(mlp_conf(updater=Sgd(0.05))).init()
        ParallelTrainer(net2, device_mesh(), mode="sync").fit(
            ArrayDataSetIterator(x, y, batch_size=24, shuffle=False),
            epochs=2, steps_per_execution=4)

        assert net2.iteration_count == net1.iteration_count
        for k in net1.param_table():
            np.testing.assert_allclose(np.asarray(net1.param_table()[k]),
                                       np.asarray(net2.param_table()[k]),
                                       atol=2e-5,
                                       err_msg=f"param {k} diverged")

    def test_sync_fused_drain_handles_ragged_group(self):
        """A group shorter than steps_per_execution (epoch tail) drains
        through the same machinery without error."""
        x, y = load_iris()
        net = MultiLayerNetwork(mlp_conf()).init()
        tr = ParallelTrainer(net, device_mesh(), mode="sync")
        # 96 examples / batch 24 = 4 batches vs spe=3 -> groups of 3 + 1
        tr.fit(ArrayDataSetIterator(x[:96], y[:96], batch_size=24,
                                    shuffle=False),
               epochs=1, steps_per_execution=3)
        assert net.iteration_count == 4
        for v in net.param_table().values():
            assert np.all(np.isfinite(np.asarray(v)))

    def test_averaging_fused_drain_matches_per_step(self):
        """Averaging mode with steps_per_execution: the in-scan pmean
        cadence must reproduce the per-step path exactly (same rng
        folds, same averaging boundaries)."""
        x, y = load_iris()
        x, y = x[:96], y[:96]

        def run(spe):
            net = MultiLayerNetwork(mlp_conf(updater=Sgd(0.05))).init()
            ParallelTrainer(net, device_mesh(), mode="averaging",
                            averaging_frequency=3).fit(
                ArrayDataSetIterator(x, y, batch_size=24, shuffle=False),
                epochs=2, steps_per_execution=spe)
            return net

        net1, net2 = run(1), run(4)
        assert net2.iteration_count == net1.iteration_count
        for k in net1.param_table():
            np.testing.assert_allclose(np.asarray(net1.param_table()[k]),
                                       np.asarray(net2.param_table()[k]),
                                       atol=2e-5,
                                       err_msg=f"param {k} diverged")

    def test_averaging_mode_learns(self):
        x, y = load_iris()
        net = MultiLayerNetwork(mlp_conf()).init()
        trainer = ParallelTrainer(net, device_mesh(), mode="averaging",
                                  averaging_frequency=4)
        trainer.fit(x[:144], y[:144], epochs=25, batch_size=48)
        e = net.evaluate(ArrayDataSetIterator(x, y, batch_size=150))
        assert e.accuracy() > 0.85, e.stats()

    def test_averaging_replicas_converge_to_same_params(self):
        x, y = load_iris()
        net = MultiLayerNetwork(mlp_conf()).init()
        trainer = ParallelTrainer(net, device_mesh(), mode="averaging",
                                  averaging_frequency=2)
        trainer.fit(x[:96], y[:96], epochs=2, batch_size=48)
        # after fit, params were averaged back — single copy, finite
        for k, v in net.param_table().items():
            assert np.all(np.isfinite(np.asarray(v)))


class TestParallelInference:
    def test_output_matches_model(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh())
        x = np.random.randn(13, 4).astype(np.float32)  # odd size → padding path
        out = pi.output(x)
        expected = np.asarray(net.output(x))
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_batched_requests(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh())
        reqs = [np.random.randn(n, 4).astype(np.float32) for n in (1, 3, 5)]
        outs = pi.output_batched(reqs)
        assert [o.shape[0] for o in outs] == [1, 3, 5]
        for r, o in zip(reqs, outs):
            np.testing.assert_allclose(o, np.asarray(net.output(r)), atol=1e-5)


class TestParallelInferenceCoalescing:
    """The background batching loop under concurrent load — the
    ObservablesProvider contract (`ParallelInference.java:84`): many
    small concurrent requests must execute as FEW large device batches,
    observable in the executed-batch-size histogram."""

    def test_concurrent_callers_coalesced(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh(),
                               batch_limit=64, queue_limit_ms=60.0)
        n_callers, rows = 24, 2
        xs = [np.random.randn(rows, 4).astype(np.float32)
              for _ in range(n_callers)]
        with pi:
            # warm the compile so the first batch doesn't fire alone
            pi.output(np.zeros((8, 4), np.float32))
            import threading
            futs = [None] * n_callers
            barrier = threading.Barrier(n_callers)

            def call(i):
                barrier.wait()          # all callers submit at once
                futs[i] = pi.output_async(xs[i])

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(n_callers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            outs = [futs[i].result(timeout=30) for i in range(n_callers)]
        # correctness: each caller got ITS rows back
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(o, np.asarray(net.output(x)),
                                       atol=1e-5)
        # coalescing: 24 requests must have run in far fewer device
        # batches, with at least one genuinely multi-request batch
        executed = pi.batch_size_history
        multi = [b for b in executed if b > rows]
        assert multi, f"no coalesced batch ever executed: {executed}"
        n_batches = sum(1 for b in executed if b >= rows)
        assert n_batches < n_callers / 2, (
            f"{n_callers} requests ran as {n_batches} batches "
            f"(histogram {executed}) — coalescing did not happen")

    def test_async_error_propagates_to_callers(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh(), queue_limit_ms=20.0)
        with pi:
            bad = pi.output_async(np.zeros((2, 7), np.float32))  # wrong width
            with pytest.raises(Exception):
                bad.result(timeout=30)
        # the collector must survive a poisoned batch
        pi2 = ParallelInference(net, device_mesh(), queue_limit_ms=20.0)
        with pi2:
            good = pi2.output_async(np.zeros((2, 4), np.float32))
            assert good.result(timeout=30).shape == (2, 3)

    def test_output_async_requires_start(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh())
        with pytest.raises(RuntimeError, match="start"):
            pi.output_async(np.zeros((1, 4), np.float32))

    def test_size_one_requests_coalesce_through_bucket_padding(self):
        """N threads submitting SIZE-1 requests (the ObservablesProvider
        worst case): they must execute as few multi-request device
        batches — observable as batch_size_history entries > 1 — and
        every coalesced batch rides the pad-to-bucket path (no bucket
        equals the odd coalesced sizes)."""
        import threading
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh(),
                               batch_limit=32, queue_limit_ms=60.0)
        n_callers = 16
        xs = [np.random.randn(1, 4).astype(np.float32)
              for _ in range(n_callers)]
        with pi:
            pi.output(np.zeros((8, 4), np.float32))  # warm the compile
            futs = [None] * n_callers
            barrier = threading.Barrier(n_callers)

            def call(i):
                barrier.wait()
                futs[i] = pi.output_async(xs[i])

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(n_callers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            outs = [futs[i].result(timeout=30) for i in range(n_callers)]
        for x, o in zip(xs, outs):
            assert o.shape == (1, 3)
            np.testing.assert_allclose(o, np.asarray(net.output(x)),
                                       atol=1e-5)
        executed = list(pi.batch_size_history)
        assert any(b > 1 for b in executed), (
            f"16 size-1 requests never coalesced: {executed}")
        # every async row executed exactly once (the synchronous warmup
        # call does not ride the coalescing history)
        assert sum(executed) == n_callers

    def test_shutdown_fails_pending_and_refuses_new_requests(self):
        """shutdown(): collector stops, queued requests fail instead of
        hanging at .result(), and the enqueue side stays closed."""
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh(), queue_limit_ms=5.0)
        pi.start()
        done = pi.output_async(np.zeros((2, 4), np.float32))
        assert done.result(timeout=30).shape == (2, 3)
        # stop the collector first so the next request stays queued,
        # then shutdown must fail it rather than leave it pending
        pi._running = False
        pi._queue.put(None)
        pi._collector.join(timeout=5)
        pi._collector = None
        import concurrent.futures
        fut = concurrent.futures.Future()
        pi._queue.put((np.zeros((1, 4), np.float32), fut))
        pi.shutdown()
        with pytest.raises(RuntimeError, match="stopped before"):
            fut.result(timeout=5)
        with pytest.raises(RuntimeError, match="shut down"):
            pi.output_async(np.zeros((1, 4), np.float32))
        with pytest.raises(RuntimeError, match="shut down"):
            pi.start()


class TestColdStartRace:
    def test_concurrent_cold_output_builds_once(self):
        """Two threads racing a COLD output() must share one
        trace/compile and one model.init() — the `_lock` created in
        __init__ was never acquired before the fix, so both raced
        through `_build()` (and could clobber each other's params
        mid-flight)."""
        import threading

        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh())
        builds = []
        orig_build = ParallelInference._build

        def counting_build(self):
            builds.append(threading.get_ident())
            import time
            time.sleep(0.05)      # widen the race window
            return orig_build(self)

        pi._build = counting_build.__get__(pi)
        n = 6
        outs = [None] * n
        barrier = threading.Barrier(n)

        def call(i):
            barrier.wait()
            outs[i] = pi.output(np.ones((2, 4), np.float32))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(builds) == 1, (
            f"{len(builds)} concurrent builds ran — the cold-start "
            "race is back")
        ref = np.asarray(net.output(np.ones((2, 4), np.float32)))
        for o in outs:
            np.testing.assert_allclose(o, ref, atol=1e-5)


class TestInferenceRegistryMetrics:
    def test_latency_queue_batchsize_emitted_without_device_sync(self):
        """The serving signal plane: request-latency histogram,
        queue-depth gauge, coalesced-batch-size histogram — emitted
        from the collector thread, visible on the registry, and (the
        PR-1 zero-sync contract) adding no device syncs beyond what
        output() itself already does."""
        import threading

        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry

        reg = monitor.enable(registry=MetricsRegistry())
        try:
            net = MultiLayerNetwork(mlp_conf()).init()
            pi = ParallelInference(net, device_mesh(),
                                   batch_limit=64, queue_limit_ms=40.0)
            n = 8
            with pi:
                pi.output(np.zeros((4, 4), np.float32))   # warm compile
                futs = [None] * n
                barrier = threading.Barrier(n)

                def call(i):
                    barrier.wait()
                    futs[i] = pi.output_async(
                        np.ones((2, 4), np.float32))

                threads = [threading.Thread(target=call, args=(i,))
                           for i in range(n)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                for f in futs:
                    f.result(timeout=30)
            lat = reg.timer("inference_request_latency_seconds")
            assert lat.count == n
            assert 0 < lat.sum < 60
            bs = reg.histogram("inference_batch_size")
            assert bs.count >= 1 and bs.sum == 2 * n
            # gauge exists and holds a sane point-in-time value
            assert reg.gauge("inference_queue_depth").value >= 0
            for fam in ("inference_request_latency_seconds",
                        "inference_batch_size", "inference_queue_depth"):
                assert fam in reg.exposition()
        finally:
            monitor.disable()

    def test_metrics_off_when_monitoring_disabled(self):
        from deeplearning4j_tpu import monitor

        monitor.disable()
        net = MultiLayerNetwork(mlp_conf()).init()
        pi = ParallelInference(net, device_mesh(), queue_limit_ms=5.0)
        with pi:
            assert pi.output_async(
                np.zeros((2, 4), np.float32)).result(timeout=30) \
                .shape == (2, 3)
        assert pi._metrics() is None
