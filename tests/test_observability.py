"""Observability plane: per-request traces, fleet telemetry federation,
SLO burn-rate, and the control-plane flight recorder (monitor/reqtrace,
monitor/federate, monitor/slo, monitor/flightrec) — plus the tracer's
ring-overflow accounting and the exposition escaping round trip.
"""

import json
import re
import urllib.request

import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import (
    FlightRecorder,
    MetricsAggregator,
    FederationCollector,
    FederationPublisher,
    MetricsRegistry,
    RequestTrace,
    SLOObjective,
    SLOTracker,
    Tracer,
    export_snapshot,
    mint_trace_id,
)
from deeplearning4j_tpu.monitor.federate import ingest_elastic_status
from deeplearning4j_tpu.monitor.registry import (
    _escape_label_value,
    _unescape_label_value,
)
from deeplearning4j_tpu.monitor.reqtrace import _tid_for
from deeplearning4j_tpu.streaming.ndarray import LocalQueueTransport


@pytest.fixture
def mon():
    """Fresh registry+tracer swapped in globally; full restore after."""
    reg, tr = MetricsRegistry(), Tracer()
    monitor.enable(registry=reg, tracer=tr)
    yield reg, tr
    monitor.disable()
    monitor._STATE.registry = monitor.GLOBAL_REGISTRY
    monitor._STATE.tracer = monitor.GLOBAL_TRACER


# the exposition grammar we promise scrapers (Prometheus text 0.0.4)
_EXPO_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\")*\})?"
    r" (\+Inf|-Inf|NaN|[-+0-9.e]+)$")


def _assert_exposition_parses(text):
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), f"bad exposition line: {line!r}"


# =====================================================================
# tracer ring-overflow accounting (tracer_events_dropped_total)
# =====================================================================
class TestTracerDropAccounting:
    def test_ring_overflow_counts_drops(self, tmp_path):
        tr = Tracer(max_events=4)
        tr.enabled = True
        for i in range(6):
            tr.complete_between(f"s{i}", 0.0, 1.0)
        assert tr.events_dropped == 2
        out = tmp_path / "t.json"
        tr.export_chrome_trace(str(out))
        doc = json.loads(out.read_text())
        assert doc["otherData"]["events_dropped"] == 2
        tr.clear()
        assert tr.events_dropped == 0

    def test_drop_counter_metric_wired_by_enable(self, mon):
        reg, tr = mon
        # shrink the ring in place and overflow it
        from collections import deque
        tr._events = deque(maxlen=2)
        for i in range(5):
            tr.instant(f"i{i}")
        fam = reg.snapshot().get("tracer_events_dropped_total")
        assert fam is not None
        assert fam["values"][0]["value"] == 3


# =====================================================================
# exposition escaping round trip (label values per Prometheus 0.0.4)
# =====================================================================
class TestExpositionEscaping:
    @pytest.mark.parametrize("raw", [
        'plain', 'quo"te', 'back\\slash', 'new\nline',
        'all\\of"them\ntogether', '\\n literal', ''])
    def test_label_value_round_trip(self, raw):
        assert _unescape_label_value(_escape_label_value(raw)) == raw

    def test_escaped_values_scrape_clean(self):
        reg = MetricsRegistry()
        reg.counter("odd_total", model='a"b\\c\nd').inc()
        text = reg.exposition()
        _assert_exposition_parses(text)
        line = next(l for l in text.splitlines()
                    if l.startswith("odd_total"))
        val = line[line.index('model="') + len('model="'):line.rindex('"')]
        assert _unescape_label_value(val) == 'a"b\\c\nd'

    def test_help_newlines_escaped(self):
        reg = MetricsRegistry()
        reg.counter("h_total", help="line one\nline two").inc()
        text = reg.exposition()
        assert "# HELP h_total line one\\nline two" in text
        _assert_exposition_parses(text)


# =====================================================================
# flight recorder
# =====================================================================
class TestFlightRecorder:
    def test_ring_bounds_and_drop_count(self):
        rec = FlightRecorder(capacity=3)
        for i in range(5):
            rec.record("tick", i=i)
        assert len(rec) == 3
        assert rec.dropped == 2
        seqs = [e["seq"] for e in rec.events()]
        assert seqs == [3, 4, 5]

    def test_kind_filter_and_last(self):
        rec = FlightRecorder()
        rec.record("swap", model="m")
        rec.record("publish", model="m")
        rec.record("swap", model="n")
        assert [e["model"] for e in rec.events(kind="swap")] == ["m", "n"]
        assert len(rec.events(last=1)) == 1

    def test_durable_jsonl_and_dump(self, tmp_path):
        live = tmp_path / "live.jsonl"
        rec = FlightRecorder(capacity=8, path=str(live))
        rec.record("deploy", model="m", version=1)
        rec.record("swap", model="m", from_version=1, to_version=2)
        lines = [json.loads(l) for l in live.read_text().splitlines()]
        assert [e["kind"] for e in lines] == ["deploy", "swap"]
        out = tmp_path / "dump.jsonl"
        text = rec.dump(str(out))
        assert text == out.read_text()
        assert json.loads(text.splitlines()[-1])["to_version"] == 2

    def test_never_raises_on_bad_path(self):
        rec = FlightRecorder(path="/nonexistent-dir/x.jsonl")
        rec.record("tick")             # swallowed OSError
        rec.dump("/nonexistent-dir/y.jsonl")
        assert len(rec) == 1

    def test_registry_publish_records_event(self, tmp_path):
        from deeplearning4j_tpu.monitor.flightrec import (
            GLOBAL_FLIGHT_RECORDER,
        )
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=2, n_out=3))
            .layer(OutputLayer(n_in=3, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build()).init()
        before = len(GLOBAL_FLIGHT_RECORDER.events(kind="publish"))
        reg = ModelRegistry(tmp_path / "models")
        v = reg.publish("flightrec-probe", net)
        evs = GLOBAL_FLIGHT_RECORDER.events(kind="publish")
        assert len(evs) == before + 1
        assert evs[-1]["model"] == "flightrec-probe"
        assert evs[-1]["version"] == v


# =====================================================================
# SLO objective + burn rate
# =====================================================================
class TestSLO:
    def test_objective_validation(self):
        with pytest.raises(ValueError):
            SLOObjective()                      # no axis
        with pytest.raises(ValueError):
            SLOObjective(ttft_s=1.0, target=1.0)

    def test_judge_axes(self):
        o = SLOObjective(ttft_s=0.5, tpot_s=0.1)
        assert o.judge(0.4, 0.05)
        assert not o.judge(0.6, 0.05)           # ttft blown
        assert not o.judge(0.4, 0.2)            # tpot blown
        assert o.judge(0.4, None)               # single-token: no tpot
        assert not o.judge(None, 0.05)          # judged axis missing

    def test_burn_rate_math(self):
        t = SLOTracker(SLOObjective(ttft_s=0.5, target=0.9,
                                    window_s=60.0))
        now = 1000.0
        for _ in range(9):
            t.record(ttft=0.1, now=now)
        t.record(ttft=9.0, now=now)             # 1 bad of 10
        # bad fraction 0.1 / budget 0.1 → burning exactly at rate
        assert t.burn_rate(now=now) == pytest.approx(1.0)
        assert t.good_total == 9 and t.bad_total == 1
        assert t.window_counts(now=now) == {"good": 9, "bad": 1}

    def test_shed_spends_budget_and_window_prunes(self):
        t = SLOTracker(SLOObjective(ttft_s=0.5, target=0.5,
                                    window_s=10.0))
        t.record_shed(now=0.0)
        assert t.burn_rate(now=0.0) == pytest.approx(2.0)
        # the shed ages out of the window; totals keep it
        assert t.burn_rate(now=100.0) == 0.0
        assert t.bad_total == 1


# =====================================================================
# request traces (unit level — serving integration in test_serving_trace)
# =====================================================================
class TestRequestTrace:
    def test_phases_flush_to_tracer_on_one_track(self, mon):
        _, tr = mon
        t = RequestTrace(model="m")
        t.phase("queued", 1.0, 2.0)
        t.phase("prefill", 2.0, 3.0, slot=0)
        t.event("cow_fork", slot=0)
        t.finish(status="ok", tokens=4)
        tid = _tid_for(t.trace_id)
        evs = [e for e in tr._events if e.get("tid") == tid]
        names = [e["name"] for e in evs]
        assert "thread_name" in names           # lane metadata
        assert "req/queued" in names and "req/prefill" in names
        assert "req/cow_fork" in names and "req/lifetime" in names
        life = next(e for e in evs if e["name"] == "req/lifetime")
        assert life["args"]["status"] == "ok"
        assert life["args"]["trace_id"] == t.trace_id

    def test_finish_idempotent_and_offline_safe(self):
        # no tracer enabled: finish must be a cheap no-op, not a crash
        t = RequestTrace()
        t.phase("queued", 0.0, 1.0)
        t.finish()
        first = t.t_finished
        t.finish(status="error")
        assert t.t_finished == first and t.status == "ok"

    def test_exemplar_sink_samples(self, tmp_path):
        from deeplearning4j_tpu.monitor.reqtrace import (
            clear_exemplar_sink,
            set_exemplar_sink,
        )
        sink = tmp_path / "ex.jsonl"
        set_exemplar_sink(str(sink), sample_every=2)
        try:
            ids = []
            for _ in range(4):
                t = RequestTrace()
                ids.append(t.trace_id)
                t.finish()
        finally:
            clear_exemplar_sink()
        kept = [json.loads(l)["trace_id"]
                for l in sink.read_text().splitlines()]
        assert kept == [ids[1], ids[3]]         # every 2nd

    def test_mint_trace_id_unique(self):
        assert mint_trace_id() != mint_trace_id()
        assert len(mint_trace_id()) == 16


# =====================================================================
# federation: many registries, one /metrics
# =====================================================================
def _worker_registry(reqs, depth, lat):
    reg = MetricsRegistry()
    reg.counter("serving_requests_total", model="m").inc(reqs)
    reg.gauge("serving_queue_depth").set(depth)
    h = reg.histogram("ttft_seconds", buckets=(0.1, 1.0))
    h.observe(lat)
    return reg


class TestFederation:
    def test_merge_semantics(self):
        agg = MetricsAggregator()
        e1 = export_snapshot(_worker_registry(3, 5, 0.05), "w1")
        e2 = export_snapshot(_worker_registry(4, 7, 5.0), "w2")
        e2["ts"] = e1["ts"] + 1.0               # w2 is newer
        agg.ingest(e1)
        agg.ingest(json.dumps(e2).encode())     # bytes path
        assert agg.workers() == ["w1", "w2"]
        snap = agg.snapshot()
        # counters sum
        assert snap["serving_requests_total"]["values"][0]["value"] == 7
        # gauges: last write (newest snapshot) wins
        assert snap["serving_queue_depth"]["values"][0]["value"] == 7
        # histograms bucket-merge
        h = snap["ttft_seconds"]["values"][0]
        assert h["count"] == 2
        assert h["bucket_counts"][0] == 1       # one obs ≤ 0.1

    def test_exposition_worker_labels_and_grammar(self):
        agg = MetricsAggregator()
        agg.ingest_registry(_worker_registry(1, 1, 0.5), "w1")
        agg.ingest_registry(_worker_registry(2, 2, 2.0), "w2")
        text = agg.exposition()
        _assert_exposition_parses(text)
        assert 'worker="w1"' in text and 'worker="w2"' in text
        # merged (unlabeled-by-worker) counter series exists too
        merged = [l for l in text.splitlines()
                  if l.startswith("serving_requests_total")
                  and "worker=" not in l]
        assert merged and float(merged[0].rsplit(" ", 1)[1]) == 3.0
        # +Inf bucket rows carry the total count
        inf = [l for l in text.splitlines()
               if l.startswith("ttft_seconds_bucket")
               and 'le="+Inf"' in l and "worker=" not in l]
        assert inf and inf[0].endswith(" 2")

    def test_bucket_layout_mismatch_degrades(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.histogram("x_seconds", buckets=(0.1, 1.0)).observe(0.5)
        r2.histogram("x_seconds", buckets=(0.2, 2.0)).observe(0.5)
        agg = MetricsAggregator()
        agg.ingest_registry(r1, "a")
        agg.ingest_registry(r2, "b")
        merged = agg.snapshot()["x_seconds"]["values"][0]
        assert merged["count"] == 2
        assert "bucket_counts" not in merged    # sum/count only
        _assert_exposition_parses(agg.exposition())

    def test_escaped_labels_survive_federation(self):
        reg = MetricsRegistry()
        reg.counter("odd_total", model='a"b').inc()
        agg = MetricsAggregator()
        agg.ingest_registry(reg, "w1")
        text = agg.exposition()
        _assert_exposition_parses(text)
        assert 'model="a\\"b"' in text

    def test_transport_pipe(self):
        tr = LocalQueueTransport()
        pub1 = FederationPublisher(tr, "fed", "w1",
                                   registry=_worker_registry(2, 1, 0.3))
        pub2 = FederationPublisher(tr, "fed", "w2",
                                   registry=_worker_registry(5, 2, 0.4))
        pub1.publish_once()
        pub2.publish_once()
        col = FederationCollector(tr, "fed")
        assert col.poll() == 2
        snap = col.aggregator.snapshot()
        assert snap["serving_requests_total"]["values"][0]["value"] == 7
        assert col.aggregator.workers() == ["w1", "w2"]

    def test_ingest_elastic_status(self):
        status = {"members": {
            "tok1": {"host": "h", "device_count": 1,
                     "info": {"metrics": export_snapshot(
                         _worker_registry(1, 1, 0.1), "tok1")}},
            "tok2": {"host": "h", "device_count": 1, "info": {}},
        }}
        agg = MetricsAggregator()
        assert ingest_elastic_status(status, agg) == 1
        assert agg.workers() == ["tok1"]

    def test_elastic_client_heartbeat_carries_metrics(self, mon):
        reg, _ = mon
        reg.counter("training_steps_total").inc(7)
        from deeplearning4j_tpu.parallel.elastic import ElasticClient
        c = ElasticClient("127.0.0.1:1", "tokX")
        c.federate_metrics()
        export = c._info["metrics"]
        assert export["worker"] == "tokX"
        agg = MetricsAggregator()
        agg.ingest(export)
        snap = agg.snapshot()
        assert snap["training_steps_total"]["values"][0]["value"] == 7


# =====================================================================
# UI: aggregator as /metrics source + /events flight-recorder route
# =====================================================================
class TestObservabilityUI:
    def test_metrics_route_serves_aggregator(self, mon):
        from deeplearning4j_tpu.ui import UIServer
        agg = MetricsAggregator()
        agg.ingest_registry(_worker_registry(3, 1, 0.2), "w1")
        agg.ingest_registry(_worker_registry(4, 2, 0.3), "w2")
        ui = UIServer(registry=agg).start()
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/metrics",
                timeout=10).read().decode()
        finally:
            ui.stop()
        _assert_exposition_parses(text)
        assert 'worker="w1"' in text and 'worker="w2"' in text

    def test_events_route_renders_flight_recorder(self):
        from deeplearning4j_tpu.monitor.flightrec import (
            GLOBAL_FLIGHT_RECORDER,
        )
        from deeplearning4j_tpu.ui import UIServer
        GLOBAL_FLIGHT_RECORDER.record("swap", model="ui-probe",
                                      from_version=1, to_version=2)
        ui = UIServer().start()
        try:
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(base + "/events",
                                          timeout=10).read().decode()
            assert "ui-probe" in html and "swap" in html
            doc = json.loads(urllib.request.urlopen(
                base + "/events?format=json&kind=swap",
                timeout=10).read().decode())
            assert any(e["model"] == "ui-probe"
                       for e in doc["events"])
        finally:
            ui.stop()
