"""Sampled speculation, truncated-layer drafter, radix prefix cache.

Unit-level contracts for the three PR-18 levers (docs/SERVING.md):

- REJECTION SAMPLING over delta drafts (`rejection_sample_drafts`):
  accept draft `d` with prob `q_t(d)`, resample the residual with `d`
  masked out.  Pinned-key determinism, zero-support auto-rejection,
  residual support, and the acceptance identity
  `E[#accepted] = sum_x min(q_t(x), p_d(x)) = q_t(d)` are all exact or
  pinned-seed checks — the large-sample marginal test lives in
  tests/test_serving_statistical.py behind `-m statistical`.
- TRUNCATED-LAYER DRAFTER: greedy streams stay bit-equal to vanilla
  `generate()` whatever the drafts were (the acceptance oracle is the
  target's own argmax), and the drafter actually proposes on
  non-repetitive traffic where the n-gram suffix cache returns nothing.
- RADIX PREFIX CACHE: automatic block-aligned mid-prompt dedup with
  cache-held references, LRU eviction of unpinned leaves only, and
  bit-exact streams for admissions that ride matched blocks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.serving import (
    BlockAllocator,
    GenerationServer,
    PagedDecodeEngine,
)
from deeplearning4j_tpu.serving.paged import RadixPrefixCache
from deeplearning4j_tpu.zoo.transformer import (
    TransformerLM,
    generate,
    rejection_sample_drafts,
)

V, D, HEADS, LAYERS, MAXLEN = 23, 16, 4, 2, 32
BL = 4


def tiny_lm(seed=3):
    return TransformerLM(vocab_size=V, d_model=D, n_layers=LAYERS,
                         n_heads=HEADS, max_len=MAXLEN, seed=seed).init()


@pytest.fixture(scope="module")
def net():
    return tiny_lm()


@pytest.fixture(scope="module")
def prompts():
    return np.random.default_rng(5).integers(0, V, (6, 5))


@pytest.fixture(scope="module")
def ref_tokens(net, prompts):
    return generate(net, prompts, 20, temperature=0)    # [6, 20]


def drain(eng, slot2req, out, **step_kw):
    guard = 0
    while eng.active.any():
        emitted, finished = eng.step(**step_kw)
        for slot, toks in emitted.items():
            out[slot2req[slot]].extend(toks)
        for slot in finished:
            del slot2req[slot]
        guard += 1
        assert guard < 400, "engine failed to drain"


def admit_all(eng, reqs):
    admitted = eng.admit_many(reqs)
    assert len(admitted) == len(reqs)
    s2r, out = {}, {}
    for i, (slot, first, done) in enumerate(admitted):
        out[i] = [first]
        if not done:
            s2r[slot] = i
    return s2r, out


# --------------------------------------------------------------------------
# rejection-sampling math (direct calls — no engine, no model)
# --------------------------------------------------------------------------
def run_rs(probs, token_mat, n_valid, keys, *, emit_idx=None, temp=None,
           top_p=None, top_k=None):
    """Call `rejection_sample_drafts` with engine-shaped arguments."""
    S, K, _ = probs.shape
    if emit_idx is None:
        emit_idx = np.zeros(S, np.int32)
    if temp is None:
        temp = np.ones(S, np.float32)
    n_acc, final = rejection_sample_drafts(
        jnp.asarray(probs, jnp.float32),
        jnp.asarray(token_mat, jnp.int32),
        jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(keys, jnp.uint32),
        jnp.asarray(emit_idx, jnp.int32),
        jnp.asarray(temp, jnp.float32),
        None if top_p is None else jnp.asarray(top_p, jnp.float32),
        top_k)
    return np.asarray(n_acc), np.asarray(final)


def batch_keys(rng, n):
    return np.asarray(rng.integers(0, 2**32, (n, 2)), np.uint32)


class TestRejectionSamplingMath:
    """SAMPLE SIZES: the empirical checks below use n=4000 pinned-seed
    draws; a binomial proportion at p=0.6 has sigma ~= 0.0077 at that
    n, and the assertions allow ~5 sigma — deterministic under the
    pinned seed, and far beyond any plausible implementation drift."""

    def test_deterministic_under_fixed_keys(self):
        rng = np.random.default_rng(11)
        S, K = 4, 4
        probs = rng.dirichlet(np.ones(V), (S, K)).astype(np.float32)
        token_mat = rng.integers(0, V, (S, K)).astype(np.int32)
        n_valid = np.array([K, K, 2, 1], np.int32)
        keys = batch_keys(rng, S)
        a1 = run_rs(probs, token_mat, n_valid, keys)
        a2 = run_rs(probs, token_mat, n_valid, keys)
        np.testing.assert_array_equal(a1[0], a2[0])
        np.testing.assert_array_equal(a1[1], a2[1])
        # a different key moves at least one row's outcome
        other = run_rs(probs, token_mat, n_valid, batch_keys(rng, S))
        assert (a1[0] != other[0]).any() or (a1[1] != other[1]).any()

    def test_zero_support_draft_always_rejected(self):
        """A draft outside the target's top-k filter has q_t(d) = 0
        exactly — `u ~ U[0,1) < 0` never fires, and the residual can
        never resample it either."""
        n = 512
        rng = np.random.default_rng(12)
        probs = np.full((n, 2, V), 1e-4, np.float32)
        probs[:, :, 0] = 0.6           # top-2 = tokens {0, 1}
        probs[:, :, 1] = 0.3
        probs /= probs.sum(-1, keepdims=True)
        dead = 7                       # outside top-2: filtered to -inf
        token_mat = np.zeros((n, 2), np.int32)
        token_mat[:, 1] = dead
        n_valid = np.full(n, 2, np.int32)
        n_acc, final = run_rs(probs, token_mat, n_valid,
                              batch_keys(rng, n), top_k=2)
        assert (n_acc == 0).all()
        assert (final != dead).all()
        assert np.isin(final, [0, 1]).all()

    def test_residual_masks_rejected_draft(self):
        """With support {0, 1} and draft 0, every rejection must emit
        token 1 — the residual `max(0, q_t - delta_d)` has exactly one
        surviving atom."""
        n = 2048
        rng = np.random.default_rng(13)
        probs = np.zeros((n, 2, V), np.float32)
        probs[:, :, 0] = 0.6
        probs[:, :, 1] = 0.4
        token_mat = np.zeros((n, 2), np.int32)    # draft token 0
        n_valid = np.full(n, 2, np.int32)
        n_acc, final = run_rs(probs, token_mat, n_valid,
                              batch_keys(rng, n))
        rejected = n_acc == 0
        assert rejected.any() and (~rejected).any()
        assert (final[rejected] == 1).all()

    def test_acceptance_identity(self):
        """`E[accepted] = sum_x min(q_t(x), p_d(x)) = q_t(d)` for a
        delta draft: the empirical acceptance frequency over 4000
        pinned-seed rows tracks q_t(d) = 0.6 (tolerance ~5 sigma)."""
        n = 4000
        rng = np.random.default_rng(14)
        probs = np.zeros((n, 2, V), np.float32)
        probs[:, :, 3] = 0.6
        probs[:, :, 4] = 0.25
        probs[:, :, 5] = 0.15
        token_mat = np.full((n, 2), 3, np.int32)  # draft token 3
        n_valid = np.full(n, 2, np.int32)
        n_acc, _ = run_rs(probs, token_mat, n_valid, batch_keys(rng, n))
        assert abs(n_acc.mean() - 0.6) < 0.04

    def test_lanewise_truncation_at_first_rejection(self):
        """top_k=1 makes q_t one-hot: a draft equal to the argmax is
        accepted with prob 1, any other rejected with prob 1 — so
        acceptance counts and the final token are fully determined."""
        rng = np.random.default_rng(15)
        S, K = 3, 4
        probs = np.full((S, K, V), 1e-6, np.float32)
        probs[:, :, 2] = 0.9                      # argmax everywhere
        token_mat = np.full((S, K), 2, np.int32)
        token_mat[0, 1] = 9       # first draft wrong -> n_acc 0
        token_mat[1, 2] = 9       # second draft wrong -> n_acc 1
        n_valid = np.full(S, K, np.int32)         # row 2: all drafts ok
        n_acc, final = run_rs(probs, token_mat, n_valid,
                              batch_keys(rng, S), top_k=1)
        np.testing.assert_array_equal(n_acc, [0, 1, 3])
        # rows 0/1 resample the one-hot residual... which masked its
        # only atom's competitor: the argmax survives unless IT was
        # the rejected draft (it wasn't — 9 was)
        np.testing.assert_array_equal(final, [2, 2, 2])

    def test_greedy_rows_guarded(self):
        """temp == 0 rows run under a guard temperature and must stay
        finite — the engine ignores their outputs (greedy slots keep
        the argmax oracle) but NaNs would poison the whole dispatch."""
        rng = np.random.default_rng(16)
        probs = rng.dirichlet(np.ones(V), (2, 3)).astype(np.float32)
        token_mat = rng.integers(0, V, (2, 3)).astype(np.int32)
        n_acc, final = run_rs(probs, token_mat,
                              np.array([3, 3], np.int32),
                              batch_keys(rng, 2),
                              temp=np.array([0.0, 1.0], np.float32))
        assert (0 <= final).all() and (final < V).all()
        assert (0 <= n_acc).all() and (n_acc <= 2).all()


# --------------------------------------------------------------------------
# engine: sampled speculation
# --------------------------------------------------------------------------
class TestSampledSpeculation:
    def test_requires_speculative(self, net):
        with pytest.raises(ValueError, match="spec_sampled"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=BL,
                              spec_sampled=True)

    def test_mixed_wave_emits_and_conserves(self, net, prompts,
                                            ref_tokens):
        """A mixed greedy+sampled wave under spec_sampled=True: greedy
        slots stay bit-equal to vanilla generate() (their oracle is
        untouched), sampled slots emit exactly n_tokens of in-vocab
        ids, drafts flow to sampled slots too, and the goodput ledger
        stays conserved."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=48, block_len=BL,
                                speculative=4, spec_sampled=True)
        n = 20
        reqs = [dict(prompt_ids=prompts[0], n_tokens=n),
                dict(prompt_ids=prompts[1], n_tokens=n, temperature=1.0,
                     rng=np.array([0, 7], np.uint32)),
                dict(prompt_ids=prompts[2], n_tokens=n),
                dict(prompt_ids=prompts[3], n_tokens=n, temperature=0.8,
                     top_p=0.95, rng=np.array([0, 9], np.uint32))]
        s2r, out = admit_all(eng, reqs)
        drain(eng, s2r, out, speculate=True)
        for i in (0, 2):
            np.testing.assert_array_equal(
                np.asarray(out[i], np.int64),
                np.asarray(ref_tokens[i], np.int64))
        for i in (1, 3):
            assert len(out[i]) == n
            assert all(0 <= t < V for t in out[i])
        assert eng.spec_dispatches_total > 0
        # sampled slots took real drafts (depth > 1) at least once
        assert eng.spec_proposed_total > 0
        assert eng.spec_accepted_total <= eng.spec_proposed_total
        assert eng.goodput.conserved()

    def test_sampled_slots_stay_depth_one_by_default(self, net, prompts):
        """spec_sampled=False (the default): sampled slots ride the
        dispatch at depth 1 — the PR-14 contract that sampled streams
        are bit-equal to the spec-free engine stays test-enforced in
        test_serving_spec.py; here we pin the counter shape."""
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32, block_len=BL,
                                speculative=4)
        reqs = [dict(prompt_ids=prompts[0], n_tokens=12, temperature=1.0,
                     rng=np.array([0, 3], np.uint32))]
        s2r, out = admit_all(eng, reqs)
        drain(eng, s2r, out, speculate=True)
        assert eng.spec_proposed_total == 0    # no sampled drafting
        assert eng.goodput.conserved()


# --------------------------------------------------------------------------
# engine: truncated-layer drafter
# --------------------------------------------------------------------------
class TestTruncatedDrafter:
    def test_requires_speculative(self, net):
        with pytest.raises(ValueError, match="spec_draft_layers"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=BL,
                              spec_draft_layers=1)

    def test_must_truncate_strictly(self, net):
        with pytest.raises(ValueError, match="strict truncation"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=BL,
                              speculative=4, spec_draft_layers=LAYERS)

    def test_greedy_parity_with_drafting(self, net, prompts, ref_tokens):
        """Whatever the truncated model drafts, greedy emission equals
        vanilla generate() bit-for-bit — the verify dispatch's argmax
        is the oracle, drafts only set how far one dispatch reaches."""
        eng = PagedDecodeEngine(net, n_slots=4, n_blocks=48, block_len=BL,
                                speculative=4, spec_draft_layers=1)
        reqs = [dict(prompt_ids=prompts[i], n_tokens=20)
                for i in range(4)]
        s2r, out = admit_all(eng, reqs)
        drain(eng, s2r, out, speculate=True)
        for i in range(4):
            np.testing.assert_array_equal(
                np.asarray(out[i], np.int64),
                np.asarray(ref_tokens[i], np.int64))
        # random prompts: the n-gram suffix cache starts empty, so the
        # truncated drafter carried real proposals
        assert eng.spec_proposed_by["truncated"] > 0
        assert eng.spec_draft_dispatches_total > 0
        assert (eng.spec_proposed_by["ngram"]
                + eng.spec_proposed_by["truncated"]
                == eng.spec_proposed_total)
        assert (eng.spec_accepted_by["ngram"]
                + eng.spec_accepted_by["truncated"]
                == eng.spec_accepted_total)
        assert eng.goodput.conserved()

    def test_proposer_restriction(self, net, prompts, ref_tokens):
        """`proposers=("truncated",)` (the scheduler's arbitration when
        the n-gram EWMA collapses) keeps the n-gram cache silent."""
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32, block_len=BL,
                                speculative=4, spec_draft_layers=1)
        reqs = [dict(prompt_ids=prompts[0], n_tokens=20)]
        s2r, out = admit_all(eng, reqs)
        drain(eng, s2r, out, speculate=True, proposers=("truncated",))
        np.testing.assert_array_equal(
            np.asarray(out[0], np.int64),
            np.asarray(ref_tokens[0], np.int64))
        assert eng.spec_proposed_by["ngram"] == 0
        assert eng.spec_proposed_by["truncated"] > 0


# --------------------------------------------------------------------------
# radix prefix cache: tree unit level
# --------------------------------------------------------------------------
class TestRadixTree:
    def _cache(self, n_blocks=32):
        alloc = BlockAllocator(n_blocks)
        return alloc, RadixPrefixCache(alloc, BL)

    def test_insert_match_roundtrip(self):
        alloc, cache = self._cache()
        toks = list(range(12))
        blocks = alloc.allocate(3)
        assert cache.insert(toks, blocks) == 3
        assert cache.nodes == 1
        assert all(alloc.refcount(b) == 2 for b in blocks)
        m, got = cache.match(toks + [99])
        assert m == 12 and got == blocks
        # a diverging prompt matches only the shared leading blocks
        m, got = cache.match(toks[:8] + [99, 98, 97, 96])
        assert m == 8 and got == blocks[:2]

    def test_split_on_divergence(self):
        alloc, cache = self._cache()
        a = alloc.allocate(3)
        cache.insert(list(range(12)), a)
        b = alloc.allocate(3)
        # same first block, divergent afterwards -> split at boundary
        cache.insert(list(range(4)) + [20, 21, 22, 23, 24, 25, 26, 27], b)
        assert cache.nodes == 3          # upper + two tails
        # the shared first block was NOT re-referenced: the tree keeps
        # its original block, the new edge holds only the tail
        assert alloc.refcount(a[0]) == 2
        assert alloc.refcount(b[0]) == 1   # caller's ref only
        m, got = cache.match(list(range(4)) + [20, 21, 22, 23])
        assert m == 8 and got == [a[0], b[1]]

    def test_cache_outlives_the_inserter(self):
        """The cache holds its OWN reference per block: the inserting
        slot's release leaves the prefix resident (the automatic
        version of register_prefix's pin)."""
        alloc, cache = self._cache()
        blocks = alloc.allocate(2)
        cache.insert(list(range(8)), blocks)
        alloc.free(blocks)               # the slot finished
        assert all(alloc.refcount(b) == 1 for b in blocks)
        m, got = cache.match(list(range(8)) + [1])
        assert m == 8 and got == blocks

    def test_evict_lru_leaves_first(self):
        alloc, cache = self._cache()
        a = alloc.allocate(2)
        cache.insert([1, 2, 3, 4, 5, 6, 7, 8], a)
        b = alloc.allocate(2)
        cache.insert([9, 10, 11, 12, 13, 14, 15, 16], b)
        alloc.free(a)
        alloc.free(b)
        cache.match([9, 10, 11, 12])     # touch b: a becomes LRU
        freed = cache.evict_lru()
        assert freed == 2
        assert cache.nodes == 1
        assert all(alloc.refcount(x) == 0 for x in a)
        m, _ = cache.match([1, 2, 3, 4])
        assert m == 0                    # a is gone
        m, _ = cache.match([9, 10, 11, 12])
        assert m == 4                    # b survives

    def test_pinned_nodes_never_evict(self):
        alloc, cache = self._cache()
        a = alloc.allocate(1)
        cache.insert([1, 2, 3, 4], a)
        for n in cache._iter_nodes():
            n.pinned = True
        assert cache.evict_lru() == 0
        assert cache.evictable_blocks == 0

    def test_clear_releases_everything(self):
        alloc, cache = self._cache()
        free0 = alloc.free_blocks
        a = alloc.allocate(2)
        cache.insert([1, 2, 3, 4, 5, 6, 7, 8], a)
        alloc.free(a)
        assert cache.clear() == 2
        assert cache.nodes == 0
        assert alloc.free_blocks == free0


# --------------------------------------------------------------------------
# radix prefix cache: engine level
# --------------------------------------------------------------------------
class TestRadixEngine:
    def test_mode_validated(self, net):
        with pytest.raises(ValueError, match="prefix_cache"):
            PagedDecodeEngine(net, n_slots=2, n_blocks=16, block_len=BL,
                              prefix_cache="lru")

    def test_auto_dedup_is_bit_exact(self, net):
        """Two admissions sharing two full blocks of prompt: the second
        rides the first's cached blocks (no register_prefix anywhere)
        and still emits exactly what a private-prefill engine does."""
        rng = np.random.default_rng(21)
        shared = rng.integers(0, V, 8)
        p1 = np.concatenate([shared, rng.integers(0, V, 2)])
        p2 = np.concatenate([shared, rng.integers(0, V, 2)])
        ref = generate(net, np.stack([p1, p2]), 12, temperature=0)

        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32, block_len=BL,
                                prefix_cache="radix")
        s2r, out = admit_all(eng, [dict(prompt_ids=p1, n_tokens=12)])
        drain(eng, s2r, out)
        res1 = out[0]
        assert eng.radix_hit_tokens_total == 0   # first ever admission
        s2r, out = admit_all(eng, [dict(prompt_ids=p2, n_tokens=12)])
        drain(eng, s2r, out)
        res2 = out[0]
        np.testing.assert_array_equal(np.asarray(res1, np.int64),
                                      np.asarray(ref[0], np.int64))
        np.testing.assert_array_equal(np.asarray(res2, np.int64),
                                      np.asarray(ref[1], np.int64))
        assert eng.radix_hit_tokens_total == 8   # both full blocks
        assert eng.prefix_hits_total == 1
        assert eng.prefix_tokens_saved_total == 8

    def test_full_prompt_match_is_capped(self, net):
        """An identical prompt must still compute its own first token:
        the match is capped one block below the full prompt, so the
        suffix-extension path always runs (no cached probs exist)."""
        rng = np.random.default_rng(22)
        p = rng.integers(0, V, 8)        # exactly two blocks
        ref = generate(net, p[None, :], 10, temperature=0)[0]
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=32, block_len=BL,
                                prefix_cache="radix")
        for _ in range(2):
            s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=10)])
            drain(eng, s2r, out)
            np.testing.assert_array_equal(np.asarray(out[0], np.int64),
                                          np.asarray(ref, np.int64))
        assert eng.radix_hit_tokens_total == 4   # capped below P=8

    def test_eviction_under_pool_pressure(self, net):
        """A pool too small to hold every cached prefix evicts radix
        LRU leaves instead of refusing admission — and the eviction
        counter records it."""
        rng = np.random.default_rng(23)
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=10, block_len=BL,
                                prefix_cache="radix")
        for i in range(6):
            p = rng.integers(0, V, 8)
            s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=6)])
            drain(eng, s2r, out)
            assert len(out[0]) == 6
        assert eng.radix_evictions_total > 0
        assert eng.goodput.conserved()

    def test_budget_ignores_radix_blocks(self, net):
        """Radix-held blocks are reclaimable, not pinned capacity:
        check_budget and can_admit treat them as available."""
        rng = np.random.default_rng(24)
        eng = PagedDecodeEngine(net, n_slots=2, n_blocks=10, block_len=BL,
                                prefix_cache="radix")
        p = rng.integers(0, V, 8)
        s2r, out = admit_all(eng, [dict(prompt_ids=p, n_tokens=6)])
        drain(eng, s2r, out)
        assert eng._radix.held_blocks > 0
        # a request needing nearly the whole pool must still pass
        eng.check_budget(16, 8)
        assert eng.can_admit(16, 8)
