"""Declarative alert engine (ISSUE: observability tentpole).

Contracts:

- the pending→firing→resolved state machine honors `for_s` hysteresis:
  a flap that un-breaches inside the window never fires; a sustained
  breach fires exactly once;
- absence rules fire when a previously-seen worker label vanishes from
  a federated `MetricsAggregator` (or its export goes stale), and
  resolve when it returns;
- EVERY transition — including the resolution — reaches the flight
  recorder under the rule's own event kind;
- `delta_rate`'s `unless_metric` suppresses a breach that a guard
  counter explains (a swap during a rollout is not an incident);
- `delta_rate`'s `only_if_metric` is the mirror image: the breach only
  counts when the co-metric ALSO increased (tenant shed growth is
  starvation only while the fleet keeps doing useful work);
- `burn_rate` averages engine-held history per window, ALL windows
  breaching;
- the default rule pack evaluates clean (all ok) on a healthy registry;
- rule states publish as `alert_state{alert=,severity=}` gauges.
"""

import pytest

from deeplearning4j_tpu.monitor.alerts import (
    ALERT_STATE_GAUGE,
    AlertEngine,
    AlertRule,
    default_rule_pack,
)
from deeplearning4j_tpu.monitor.federate import MetricsAggregator
from deeplearning4j_tpu.monitor.flightrec import FlightRecorder
from deeplearning4j_tpu.monitor.registry import MetricsRegistry


def gauge_snap(value, metric="m", **labels):
    return {metric: {"type": "gauge", "help": "",
                     "values": [{"labels": labels, "value": value}]}}


def make_engine(source, *rules, registry=None):
    rec = FlightRecorder()
    eng = AlertEngine(source, rules, recorder=rec,
                      registry=registry or MetricsRegistry())
    return eng, rec


def state_of(states, name):
    return next(s["state"] for s in states if s["name"] == name)


# ==================================================== threshold + for_s
class TestThresholdHysteresis:
    def test_flap_inside_window_never_fires(self):
        box = {"v": 0.0}
        eng, rec = make_engine(
            lambda: gauge_snap(box["v"]),
            AlertRule(name="hot", kind="threshold", metric="m", op=">",
                      value=10.0, for_s=5.0, event_kind="hot_ev"))
        assert state_of(eng.evaluate(now=0.0), "hot") == "ok"
        box["v"] = 99.0
        assert state_of(eng.evaluate(now=1.0), "hot") == "pending"
        box["v"] = 0.0                        # un-breach inside for_s
        assert state_of(eng.evaluate(now=3.0), "hot") == "ok"
        states = {e["state"] for e in rec.events(kind="hot_ev")}
        assert "firing" not in states
        assert "resolved" not in states       # a flap is not an incident

    def test_sustained_breach_fires_then_resolves(self):
        box = {"v": 99.0}
        eng, rec = make_engine(
            lambda: gauge_snap(box["v"]),
            AlertRule(name="hot", kind="threshold", metric="m", op=">",
                      value=10.0, for_s=5.0, severity="page",
                      event_kind="hot_ev"))
        assert state_of(eng.evaluate(now=0.0), "hot") == "pending"
        assert state_of(eng.evaluate(now=2.0), "hot") == "pending"
        states = eng.evaluate(now=6.0)        # held past for_s
        assert state_of(states, "hot") == "firing"
        assert eng.firing()[0]["name"] == "hot"
        box["v"] = 0.0
        assert state_of(eng.evaluate(now=8.0), "hot") == "ok"
        labels = [e["state"] for e in rec.events(kind="hot_ev")]
        assert labels == ["pending", "firing", "resolved"]
        resolved = rec.events(kind="hot_ev")[-1]
        assert resolved["alert"] == "hot"
        assert resolved["severity"] == "page"

    def test_missing_family_never_breaches(self):
        eng, _ = make_engine(
            lambda: {},
            AlertRule(name="hot", kind="threshold", metric="m", op=">",
                      value=10.0))
        assert state_of(eng.evaluate(now=0.0), "hot") == "ok"

    def test_label_filter_scopes_series(self):
        snap = {"m": {"type": "gauge", "help": "", "values": [
            {"labels": {"model": "a"}, "value": 99.0},
            {"labels": {"model": "b"}, "value": 1.0}]}}
        eng, _ = make_engine(
            lambda: snap,
            AlertRule(name="a-only", kind="threshold", metric="m",
                      labels={"model": "b"}, op=">", value=10.0))
        assert state_of(eng.evaluate(now=0.0), "a-only") == "ok"


# ======================================================= worker absence
class TestWorkerAbsence:
    def rule(self, **kw):
        return AlertRule(name="worker-vanished", kind="absence",
                         metric=None, severity="page",
                         event_kind="worker_vanished", **kw)

    def test_vanished_worker_fires_and_return_resolves(self):
        agg = MetricsAggregator()
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("a_total", "a").inc()
        r2.counter("b_total", "b").inc()
        agg.ingest_registry(r1, "serve0")
        agg.ingest_registry(r2, "train0")
        eng, rec = make_engine(agg, self.rule())
        assert state_of(eng.evaluate(now=0.0),
                        "worker-vanished") == "ok"
        agg.drop_worker("train0")
        states = eng.evaluate(now=1.0)
        assert state_of(states, "worker-vanished") == "firing"
        st = next(s for s in states if s["name"] == "worker-vanished")
        assert st["context"]["missing"] == ["train0"]
        agg.ingest_registry(r2, "train0")     # the publisher came back
        assert state_of(eng.evaluate(now=2.0),
                        "worker-vanished") == "ok"
        labels = [e["state"] for e in rec.events(kind="worker_vanished")]
        assert labels == ["firing", "resolved"]

    def test_stale_export_fires(self):
        agg = MetricsAggregator()
        r1 = MetricsRegistry()
        r1.counter("a_total", "a").inc()
        agg.ingest_registry(r1, "serve0")
        eng, _ = make_engine(agg, self.rule(stale_s=0.0))
        states = eng.evaluate(now=0.0)        # any age > 0.0 is stale
        assert state_of(states, "worker-vanished") == "firing"
        st = next(s for s in states if s["name"] == "worker-vanished")
        assert st["context"]["stale"] == ["serve0"]

    def test_plain_registry_source_is_inert(self):
        # worker liveness needs an aggregator; against a bare registry
        # the rule simply never matches
        eng, _ = make_engine(MetricsRegistry(), self.rule())
        assert state_of(eng.evaluate(now=0.0),
                        "worker-vanished") == "ok"


# =================================================== series absence
class TestSeriesAbsence:
    def test_vanished_series_fires(self):
        box = {"snap": {"m": {"type": "gauge", "help": "", "values": [
            {"labels": {"model": "a"}, "value": 1.0},
            {"labels": {"model": "b"}, "value": 1.0}]}}}
        eng, _ = make_engine(
            lambda: box["snap"],
            AlertRule(name="gone", kind="absence", metric="m"))
        assert state_of(eng.evaluate(now=0.0), "gone") == "ok"
        box["snap"] = gauge_snap(1.0, model="a")
        states = eng.evaluate(now=1.0)
        assert state_of(states, "gone") == "firing"
        st = next(s for s in states if s["name"] == "gone")
        assert st["context"]["missing"] == [{"model": "b"}]


# ========================================================== delta_rate
class TestDeltaRate:
    def counter_snap(self, shed, published=None):
        snap = {"serving_shed_total": {
            "type": "counter", "help": "",
            "values": [{"labels": {}, "value": shed}]}}
        if published is not None:
            snap["registry_published_total"] = {
                "type": "counter", "help": "",
                "values": [{"labels": {}, "value": published}]}
        return snap

    def test_rate_fires_and_quiescence_resolves(self):
        box = {"shed": 0.0}
        eng, rec = make_engine(
            lambda: self.counter_snap(box["shed"]),
            AlertRule(name="shed-growth", kind="delta_rate",
                      metric="serving_shed_total", op=">", value=1.0,
                      aggregate="sum", event_kind="shed_growth"))
        eng.evaluate(now=0.0)                 # primes the cursor
        box["shed"] = 100.0                   # 10/s over the interval
        assert state_of(eng.evaluate(now=10.0),
                        "shed-growth") == "firing"
        assert state_of(eng.evaluate(now=20.0),
                        "shed-growth") == "ok"
        labels = [e["state"] for e in rec.events(kind="shed_growth")]
        assert labels == ["firing", "resolved"]

    def test_counter_reset_never_negative_rate(self):
        box = {"shed": 100.0}
        eng, _ = make_engine(
            lambda: self.counter_snap(box["shed"]),
            AlertRule(name="shed-growth", kind="delta_rate",
                      metric="serving_shed_total", op=">", value=-1.0))
        eng.evaluate(now=0.0)
        box["shed"] = 0.0                     # process restart
        states = eng.evaluate(now=10.0)
        st = next(s for s in states if s["name"] == "shed-growth")
        assert st["value"] == 0.0             # clamped, not -10/s

    def test_unless_metric_suppresses_rollout(self):
        box = {"swaps": 0.0, "pub": 0.0}

        def snap():
            return {
                "fleet_swaps_total": {
                    "type": "counter", "help": "",
                    "values": [{"labels": {}, "value": box["swaps"]}]},
                "registry_published_total": {
                    "type": "counter", "help": "",
                    "values": [{"labels": {}, "value": box["pub"]}]}}

        eng, _ = make_engine(
            snap,
            AlertRule(name="swap-no-pub", kind="delta_rate",
                      metric="fleet_swaps_total", op=">", value=0.0,
                      unless_metric="registry_published_total"))
        eng.evaluate(now=0.0)
        box["swaps"] += 1                     # swap WITH a publish:
        box["pub"] += 1                       # a rollout, not an alert
        assert state_of(eng.evaluate(now=10.0), "swap-no-pub") == "ok"
        box["swaps"] += 1                     # swap with NO publish
        assert state_of(eng.evaluate(now=20.0),
                        "swap-no-pub") == "firing"

    def test_only_if_metric_requires_co_increase(self):
        box = {"shed": 0.0, "useful": 0.0}

        def snap():
            return {
                "fleet_tenant_shed_total": {
                    "type": "counter", "help": "",
                    "values": [{"labels": {"tenant": "a"},
                                "value": box["shed"]}]},
                "serving_tokens_useful_total": {
                    "type": "counter", "help": "",
                    "values": [{"labels": {}, "value": box["useful"]}]}}

        eng, _ = make_engine(
            snap,
            AlertRule(name="starved", kind="delta_rate",
                      metric="fleet_tenant_shed_total", op=">",
                      value=1.0, aggregate="sum",
                      only_if_metric="serving_tokens_useful_total"))
        eng.evaluate(now=0.0)
        box["shed"] += 100                    # sheds grow, goodput flat:
        states = eng.evaluate(now=10.0)       # the fleet ISN'T healthy —
        assert state_of(states, "starved") == "ok"   # not starvation
        st = next(s for s in states if s["name"] == "starved")
        assert st["context"]["only_if_increase"] == 0.0
        box["shed"] += 100                    # sheds grow AND the fleet
        box["useful"] += 500                  # keeps serving: starvation
        assert state_of(eng.evaluate(now=20.0), "starved") == "firing"


# =========================================================== burn_rate
class TestBurnRate:
    def test_windowed_average_fires_and_decays(self):
        box = {"v": 20.0}
        eng, _ = make_engine(
            lambda: gauge_snap(box["v"], metric="slo_burn_rate"),
            AlertRule(name="slo-burn", kind="burn_rate",
                      metric="slo_burn_rate", op=">",
                      windows=((60.0, 14.0),)))
        assert state_of(eng.evaluate(now=0.0), "slo-burn") == "firing"
        box["v"] = 0.0                        # budget stops burning:
        eng.evaluate(now=20.0)                # avg (20+0)/2 = 10 < 14
        assert state_of(eng.evaluate(now=40.0), "slo-burn") == "ok"

    def test_all_windows_must_breach(self):
        box = {"v": 20.0}
        eng, _ = make_engine(
            lambda: gauge_snap(box["v"], metric="slo_burn_rate"),
            AlertRule(name="slo-burn", kind="burn_rate",
                      metric="slo_burn_rate", op=">",
                      windows=((60.0, 14.0), (60.0, 100.0))))
        # fast window breaches (20 > 14) but the second bound (100)
        # does not — no page
        assert state_of(eng.evaluate(now=0.0), "slo-burn") == "ok"


# ===================================================== rule validation
class TestRuleValidation:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            AlertRule(name="x", kind="vibes", metric="m")

    def test_metric_required_outside_absence(self):
        with pytest.raises(ValueError, match="metric"):
            AlertRule(name="x", kind="threshold")

    def test_burn_rate_needs_windows(self):
        with pytest.raises(ValueError, match="windows"):
            AlertRule(name="x", kind="burn_rate", metric="m")

    def test_duplicate_rule_name_rejected(self):
        eng, _ = make_engine(lambda: {})
        eng.add_rule(AlertRule(name="x", kind="threshold", metric="m"))
        with pytest.raises(ValueError, match="duplicate"):
            eng.add_rule(AlertRule(name="x", kind="threshold",
                                   metric="m"))


# ================================================== default rule pack
class TestDefaultRulePack:
    def healthy_registry(self):
        reg = MetricsRegistry()
        reg.gauge("checkpoint_last_age_seconds").set(4.0)
        reg.gauge("elastic_live_processes").set(4.0)
        reg.gauge("streaming_watermark_age_seconds").set(2.0)
        reg.gauge("slo_burn_rate").set(0.2)
        reg.counter("serving_shed_total", "sheds").inc(0)
        reg.counter("registry_resolve_fallback_total", "fallbacks")
        reg.counter("fleet_swaps_total", "swaps")
        reg.counter("registry_published_total", "publishes").inc(2)
        reg.counter("serving_radix_evictions_total", "evictions")
        reg.gauge("serving_spec_accept_rate").set(1.0)
        reg.gauge("serving_spec_accept_rate", proposer="ngram").set(0.8)
        return reg

    def test_pack_covers_the_twelve_documented_shapes(self):
        pack = default_rule_pack()
        assert sorted(r.name for r in pack) == [
            "checkpoint-staleness", "drift-gate-stuck-paused",
            "elastic-shrink", "radix-eviction-churn",
            "registry-fallback", "sampled-spec-acceptance-collapse",
            "shed-growth", "slo-burn", "swap-without-publish",
            "tenant-share-starvation", "watermark-lag",
            "worker-vanished"]
        assert len({r.event_kind for r in pack}) == len(pack)

    def test_pack_clean_on_healthy_registry(self):
        eng, rec = make_engine(self.healthy_registry(),
                               *default_rule_pack())
        # two passes so every delta-rate cursor is primed and evaluated
        eng.evaluate(now=0.0)
        states = eng.evaluate(now=10.0)
        assert all(s["state"] == "ok" for s in states), states
        assert rec.events() == []             # zero transitions

    def test_pack_fires_on_stale_checkpoint(self):
        reg = self.healthy_registry()
        reg.gauge("checkpoint_last_age_seconds").set(9999.0)
        eng, rec = make_engine(reg, *default_rule_pack())
        states = eng.evaluate(now=0.0)
        assert state_of(states, "checkpoint-staleness") == "firing"
        assert rec.events(kind="checkpoint_stale")

    def test_pack_fires_on_radix_eviction_churn(self):
        reg = self.healthy_registry()
        eng, rec = make_engine(reg, *default_rule_pack(for_s=0.0))
        eng.evaluate(now=0.0)                 # prime the delta cursor
        reg.counter("serving_radix_evictions_total").inc(500)
        states = eng.evaluate(now=10.0)       # 50/s >> 5/s bound
        assert state_of(states, "radix-eviction-churn") == "firing"
        assert rec.events(kind="radix_eviction_churn")

    def test_pack_fires_on_spec_acceptance_collapse(self):
        reg = self.healthy_registry()
        reg.gauge("serving_spec_accept_rate",
                  proposer="ngram").set(0.01)  # min over series
        eng, rec = make_engine(reg, *default_rule_pack(for_s=0.0))
        states = eng.evaluate(now=0.0)
        assert state_of(states,
                        "sampled-spec-acceptance-collapse") == "firing"
        assert rec.events(kind="spec_acceptance_collapse")

    def test_pack_fires_on_drift_gate_stuck_paused(self):
        reg = self.healthy_registry()
        reg.gauge("online_publish_paused", tag="tenant-beta").set(1.0)
        eng, rec = make_engine(reg, *default_rule_pack(
            drift_paused_for_s=5.0))
        states = eng.evaluate(now=0.0)       # breach seen, hysteresis
        assert state_of(states, "drift-gate-stuck-paused") == "pending"
        states = eng.evaluate(now=10.0)      # held past for_s -> fire
        assert state_of(states, "drift-gate-stuck-paused") == "firing"
        assert rec.events(kind="drift_gate_stuck")

    def test_pack_fires_on_tenant_share_starvation(self):
        reg = self.healthy_registry()
        shed = reg.counter("fleet_tenant_shed_total", "sheds",
                           tenant="gamma")
        useful = reg.counter("serving_tokens_useful_total", "useful")
        eng, rec = make_engine(reg, *default_rule_pack())
        eng.evaluate(now=0.0)                # prime the delta cursors
        shed.inc(100)                        # 10/s >> 1/s bound...
        useful.inc(500)                      # ...while goodput flows
        states = eng.evaluate(now=10.0)
        assert state_of(states, "tenant-share-starvation") == "firing"
        assert rec.events(kind="tenant_starvation")


# ====================================================== gauge publish
class TestStateGauges:
    def test_states_published_to_registry(self):
        out = MetricsRegistry()
        box = {"v": 99.0}
        eng = AlertEngine(
            lambda: gauge_snap(box["v"]),
            [AlertRule(name="hot", kind="threshold", metric="m",
                       op=">", value=10.0, severity="page")],
            recorder=FlightRecorder(), registry=out)
        eng.evaluate(now=0.0)
        vals = out.snapshot()[ALERT_STATE_GAUGE]["values"]
        entry = next(v for v in vals
                     if v["labels"] == {"alert": "hot",
                                        "severity": "page"})
        assert entry["value"] == 2.0          # firing
        box["v"] = 0.0
        eng.evaluate(now=1.0)
        vals = out.snapshot()[ALERT_STATE_GAUGE]["values"]
        entry = next(v for v in vals
                     if v["labels"] == {"alert": "hot",
                                        "severity": "page"})
        assert entry["value"] == 0.0          # back to ok
