"""Layer-level tests: shapes, forward semantics, serde, gradient checks.

Models the reference's gradientcheck suite
(`deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/`)
— every layer family validated against central finite differences.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.gradientcheck import check_gradients_fn, check_model_gradients
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import (
    LSTM,
    ActivationLayer,
    AutoEncoder,
    BatchNormalization,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LastTimeStep,
    LocalResponseNormalization,
    LossLayer,
    OutputLayer,
    RnnOutputLayer,
    SimpleRnn,
    SpaceToDepthLayer,
    Subsampling1DLayer,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.layers.base import layer_from_dict
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

RNG = jax.random.PRNGKey(0)


def rand(*shape, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape)


class TestShapes:
    def test_dense(self):
        l = DenseLayer(n_in=5, n_out=3)
        p = l.init_params(RNG)
        assert p["W"].shape == (5, 3) and p["b"].shape == (3,)
        y, _ = l.forward(p, {}, rand(2, 5))
        assert y.shape == (2, 3)

    def test_conv_truncate_and_same(self):
        l = ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3), stride=(2, 2))
        out = l.get_output_type(InputType.convolutional(9, 9, 3))
        assert (out.height, out.width, out.channels) == (4, 4, 8)
        p = l.init_params(RNG)
        y, _ = l.forward(p, {}, rand(2, 9, 9, 3))
        assert y.shape == (2, 4, 4, 8)

        l2 = ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3), stride=(2, 2),
                              convolution_mode=ConvolutionMode.SAME)
        out2 = l2.get_output_type(InputType.convolutional(9, 9, 3))
        assert (out2.height, out2.width) == (5, 5)
        y2, _ = l2.forward(l2.init_params(RNG), {}, rand(2, 9, 9, 3))
        assert y2.shape == (2, 5, 5, 8)

    def test_subsampling(self):
        l = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))
        y, _ = l.forward({}, {}, rand(2, 8, 8, 4))
        assert y.shape == (2, 4, 4, 4)
        # max pooling actually takes the max
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y, _ = l.forward({}, {}, x)
        np.testing.assert_allclose(np.asarray(y)[0, :, :, 0], [[5, 7], [13, 15]])

    def test_avg_pool_counts_padding_correctly(self):
        l = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2),
                             convolution_mode=ConvolutionMode.SAME)
        x = jnp.ones((1, 3, 3, 1))
        y, _ = l.forward({}, {}, x)
        np.testing.assert_allclose(np.asarray(y), np.ones((1, 2, 2, 1)))

    def test_upsampling_zeropad(self):
        y, _ = Upsampling2D(size=2).forward({}, {}, rand(1, 3, 3, 2))
        assert y.shape == (1, 6, 6, 2)
        y, _ = ZeroPaddingLayer(pad=((1, 2), (0, 3))).forward({}, {}, rand(1, 3, 3, 2))
        assert y.shape == (1, 6, 6, 2)

    def test_space_to_depth(self):
        y, _ = SpaceToDepthLayer(block_size=2).forward({}, {}, rand(1, 4, 4, 3))
        assert y.shape == (1, 2, 2, 12)

    def test_lstm_shapes(self):
        l = LSTM(n_in=6, n_out=4)
        p = l.init_params(RNG)
        assert p["W"].shape == (6, 16) and p["RW"].shape == (4, 16) and p["b"].shape == (16,)
        y, _ = l.forward(p, {}, rand(3, 7, 6))
        assert y.shape == (3, 7, 4)

    def test_lstm_forget_bias(self):
        l = LSTM(n_in=2, n_out=3, forget_gate_bias_init=1.0)
        b = l.init_params(RNG)["b"]
        np.testing.assert_allclose(b[3:6], jnp.ones(3))
        np.testing.assert_allclose(b[:3], jnp.zeros(3))

    def test_bidirectional_sums(self):
        l = GravesBidirectionalLSTM(n_in=3, n_out=4)
        p = l.init_params(RNG)
        assert set(p) == {"WF", "RWF", "bF", "pIF", "pFF", "pOF",
                          "WB", "RWB", "bB", "pIB", "pFB", "pOB"}
        y, _ = l.forward(p, {}, rand(2, 5, 3))
        assert y.shape == (2, 5, 4)

    def test_embedding(self):
        l = EmbeddingLayer(n_in=10, n_out=4)
        p = l.init_params(RNG)
        idx = jnp.array([[1], [3]])
        y, _ = l.forward(p, {}, idx)
        assert y.shape == (2, 4)
        np.testing.assert_allclose(y[0], p["W"][1] + p["b"], atol=1e-6)

    def test_batchnorm_train_vs_eval(self):
        l = BatchNormalization(n_out=4)
        p, s = l.init_params(RNG), l.init_state()
        x = rand(32, 4, seed=3) * 5 + 2
        y, s2 = l.forward(p, s, x, train=True)
        np.testing.assert_allclose(float(jnp.mean(y)), 0.0, atol=1e-5)
        np.testing.assert_allclose(float(jnp.std(y)), 1.0, atol=1e-2)
        assert not np.allclose(np.asarray(s2["mean"]), 0)
        # eval path uses running stats
        y_eval, s3 = l.forward(p, s2, x, train=False)
        assert s3 is s2

    def test_global_pooling_masked(self):
        l = GlobalPoolingLayer(pooling_type="avg")
        x = jnp.stack([jnp.ones((4, 2)), 2 * jnp.ones((4, 2))])
        mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], dtype=jnp.float32)
        y, _ = l.forward({}, {}, x, mask=mask)
        np.testing.assert_allclose(y, [[1, 1], [2, 2]])

    def test_last_time_step_masked(self):
        l = LastTimeStep()
        x = jnp.arange(12.0).reshape(1, 4, 3)
        mask = jnp.array([[1, 1, 0, 0]], dtype=jnp.float32)
        y, _ = l.forward({}, {}, x, mask=mask)
        np.testing.assert_allclose(y, [[3.0, 4.0, 5.0]])

    def test_conv1d_subsampling1d(self):
        l = Convolution1DLayer(n_in=4, n_out=6, kernel_size=3, stride=1)
        p = l.init_params(RNG)
        y, _ = l.forward(p, {}, rand(2, 8, 4))
        assert y.shape == (2, 6, 6)
        s = Subsampling1DLayer(kernel_size=2, stride=2)
        y2, _ = s.forward({}, {}, y)
        assert y2.shape == (2, 3, 6)

    def test_lrn_shape_preserved(self):
        l = LocalResponseNormalization()
        x = rand(2, 4, 4, 8)
        y, _ = l.forward({}, {}, x)
        assert y.shape == x.shape

    def test_dropout_train_only(self):
        l = DropoutLayer(dropout=0.5)
        x = jnp.ones((4, 10))
        y_eval, _ = l.forward({}, {}, x, train=False)
        np.testing.assert_allclose(y_eval, x)
        y_train, _ = l.forward({}, {}, x, train=True, rng=RNG)
        assert float(jnp.min(y_train)) == 0.0  # some dropped
        assert float(jnp.max(y_train)) == 2.0  # inverted scaling 1/0.5


class TestSerde:
    @pytest.mark.parametrize("layer", [
        DenseLayer(n_in=3, n_out=4, activation="relu", l2=1e-4),
        OutputLayer(n_in=4, n_out=2, loss="mcxent"),
        ConvolutionLayer(n_in=1, n_out=6, kernel_size=(5, 5),
                         convolution_mode=ConvolutionMode.SAME),
        SubsamplingLayer(pooling_type="avg", kernel_size=(3, 3)),
        LSTM(n_in=5, n_out=7, gate_activation="hardsigmoid"),
        GravesLSTM(n_in=5, n_out=7),
        GravesBidirectionalLSTM(n_in=5, n_out=7),
        BatchNormalization(n_out=3, decay=0.8),
        EmbeddingLayer(n_in=100, n_out=16),
        GlobalPoolingLayer(pooling_type="pnorm", pnorm=3),
        RnnOutputLayer(n_in=4, n_out=2),
        AutoEncoder(n_in=8, n_out=4, corruption_level=0.2),
        ZeroPaddingLayer(pad=2),
        LossLayer(loss="mse", activation="identity"),
    ])
    def test_roundtrip(self, layer):
        d = layer.to_dict()
        import json
        layer2 = layer_from_dict(json.loads(json.dumps(d)))
        assert layer2 == layer


class TestGradientChecks:
    """Central finite-difference validation, per layer family
    (reference GradientCheckTests / CNNGradientCheckTest /
    LSTMGradientCheckTests)."""

    def _check(self, conf, x, y, **kw):
        net = MultiLayerNetwork(conf).init()
        ok, worst, failures = check_model_gradients(net, x, y, **kw)
        assert ok, f"worst rel err {worst}; failures {failures[:5]}"

    def test_dense_mlp(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(DenseLayer(n_in=4, n_out=5, activation="tanh"))
                .layer(OutputLayer(n_in=5, n_out=3, activation="softmax", loss="mcxent"))
                .build())
        x = np.random.default_rng(0).standard_normal((6, 4))
        y = np.eye(3)[np.random.default_rng(1).integers(0, 3, 6)]
        self._check(conf, x, y)

    def test_dense_l1_l2(self):
        conf = (NeuralNetConfiguration.builder().seed(42).l2(1e-2).l1(1e-3).list()
                .layer(DenseLayer(n_in=4, n_out=5, activation="sigmoid"))
                .layer(OutputLayer(n_in=5, n_out=3, activation="identity", loss="mse"))
                .build())
        x = np.random.default_rng(0).standard_normal((5, 4))
        y = np.random.default_rng(1).standard_normal((5, 3))
        self._check(conf, x, y)

    def test_cnn(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(2, 2), stride=(1, 1),
                                        activation="tanh"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(6, 6, 2))
                .build())
        x = np.random.default_rng(0).standard_normal((3, 6, 6, 2))
        y = np.eye(2)[np.random.default_rng(1).integers(0, 2, 3)]
        self._check(conf, x, y)

    def test_batchnorm(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="identity"))
                .layer(BatchNormalization())
                .layer(ActivationLayer(activation="relu"))
                .layer(OutputLayer(n_in=6, n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        x = np.random.default_rng(0).standard_normal((8, 4))
        y = np.eye(3)[np.random.default_rng(1).integers(0, 3, 8)]
        # batch-stat path is evaluated train=False inside the checker but
        # uses running stats — use train stats by pre-populating state
        net = MultiLayerNetwork(conf).init()
        out = net.output(jnp.asarray(x))  # populate nothing; just smoke
        ok, worst, failures = check_model_gradients(net, x, y, max_rel_error=1e-4)
        assert ok, f"worst {worst} {failures[:3]}"

    def test_lstm(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(LSTM(n_in=3, n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 5, 3))
        y = np.eye(2)[rng.integers(0, 2, (2, 5))]
        self._check(conf, x, y)

    def test_graves_lstm_peepholes(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(GravesLSTM(n_in=3, n_out=4))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 3))
        y = np.eye(2)[rng.integers(0, 2, (2, 4))]
        self._check(conf, x, y)

    def test_bidirectional_lstm_masked(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(GravesBidirectionalLSTM(n_in=3, n_out=4))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 5, 3))
        y = np.eye(2)[rng.integers(0, 2, (2, 5))]
        fmask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], dtype=np.float64)
        net = MultiLayerNetwork(conf).init()
        ok, worst, failures = check_model_gradients(
            net, x, y, features_mask=fmask, labels_mask=fmask)
        assert ok, f"worst {worst} {failures[:3]}"

    def test_simple_rnn(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(SimpleRnn(n_in=3, n_out=4))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss="mcxent"))
                .build())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 5, 3))
        y = np.eye(2)[rng.integers(0, 2, (2, 5))]
        self._check(conf, x, y)

    def test_global_pooling_cnn(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="tanh"))
                .layer(GlobalPoolingLayer(pooling_type="avg"))
                .layer(OutputLayer(n_in=3, n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(5, 5, 2))
                .build())
        x = np.random.default_rng(0).standard_normal((3, 5, 5, 2))
        y = np.eye(2)[np.random.default_rng(1).integers(0, 2, 3)]
        self._check(conf, x, y)

    def test_embedding_gradient(self):
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(EmbeddingLayer(n_in=8, n_out=4, activation="tanh"))
                .layer(OutputLayer(n_in=4, n_out=3, activation="softmax", loss="mcxent"))
                .build())
        x = np.random.default_rng(0).integers(0, 8, (6, 1)).astype(np.float64)
        y = np.eye(3)[np.random.default_rng(1).integers(0, 3, 6)]
        self._check(conf, x, y)

    @pytest.mark.parametrize("loss,act", [
        ("mse", "identity"), ("mae", "identity"), ("xent", "sigmoid"),
        ("hinge", "identity"), ("poisson", "softplus"), ("squaredhinge", "identity"),
    ])
    def test_loss_functions(self, loss, act):
        """Reference LossFunctionGradientCheck."""
        conf = (NeuralNetConfiguration.builder().seed(42).list()
                .layer(DenseLayer(n_in=3, n_out=4, activation="tanh"))
                .layer(OutputLayer(n_in=4, n_out=2, activation=act, loss=loss))
                .build())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((5, 3))
        if loss in ("xent",):
            y = rng.integers(0, 2, (5, 2)).astype(np.float64)
        elif loss in ("hinge", "squaredhinge"):
            y = np.eye(2)[rng.integers(0, 2, 5)]
        elif loss == "poisson":
            y = rng.poisson(2.0, (5, 2)).astype(np.float64)
        else:
            y = rng.standard_normal((5, 2))
        self._check(conf, x, y)
