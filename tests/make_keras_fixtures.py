"""Generate REAL Keras-produced .h5 fixtures + recorded predictions.

Run offline (needs the keras pip package) to (re)build
`tests/fixtures/keras/`; the committed artifacts are genuine Keras
output, so `tests/test_keras_real_golden.py` would fail if our model of
Keras's on-disk layout drifted from what Keras actually writes — the
gap the fabricated-fixture tests in `test_keras_golden.py` cannot close
(reference vendors actual Keras files the same way:
`deeplearning4j-modelimport/src/test/resources/configs/`).

    python tests/make_keras_fixtures.py

Provenance is stamped into fixtures/keras/MANIFEST.json.
"""

import json
import os
import sys
from pathlib import Path

os.environ.setdefault("KERAS_BACKEND", "tensorflow")

import numpy as np

FIXDIR = Path(__file__).parent / "fixtures" / "keras"


def main():
    import keras
    from keras import layers

    FIXDIR.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(42)
    preds = {}

    # 1. Sequential CNN (conv same + pool + flatten + dense softmax)
    cnn = keras.Sequential([
        keras.Input(shape=(8, 8, 1)),
        layers.Conv2D(4, 3, padding="same", activation="relu", name="conv"),
        layers.MaxPooling2D(2, name="pool"),
        layers.Flatten(name="flatten"),
        layers.Dense(10, activation="softmax", name="fc"),
    ], name="seq_cnn")
    x_cnn = rng.standard_normal((2, 8, 8, 1)).astype(np.float32)
    preds["cnn_x"] = x_cnn
    preds["cnn_y"] = cnn.predict(x_cnn, verbose=0)
    cnn.save(FIXDIR / "real_cnn.h5")

    # 2. Sequential LSTM (sigmoid recurrent activation — Keras 3 default)
    lstm = keras.Sequential([
        keras.Input(shape=(4, 3)),
        layers.LSTM(5, name="lstm"),
        layers.Dense(2, activation="softmax", name="fc"),
    ], name="seq_lstm")
    x_lstm = rng.standard_normal((2, 4, 3)).astype(np.float32)
    preds["lstm_x"] = x_lstm
    preds["lstm_y"] = lstm.predict(x_lstm, verbose=0)
    lstm.save(FIXDIR / "real_lstm.h5")

    # 3. Functional branch/merge MLP (Add + Concatenate)
    inp = keras.Input(shape=(8,), name="in")
    a = layers.Dense(6, activation="relu", name="d1")(inp)
    b = layers.Dense(6, activation="tanh", name="d2")(inp)
    s = layers.Add(name="add")([a, b])
    c = layers.Concatenate(name="cat")([s, a])
    out = layers.Dense(3, activation="softmax", name="out")(c)
    func = keras.Model(inp, out, name="func_mlp")
    x_f = rng.standard_normal((3, 8)).astype(np.float32)
    preds["func_x"] = x_f
    preds["func_y"] = func.predict(x_f, verbose=0)
    func.save(FIXDIR / "real_func.h5")

    # 4. Sequential with BatchNorm (inference uses moving stats) +
    #    SeparableConv2D. Train one step so moving stats are non-trivial.
    bn = keras.Sequential([
        keras.Input(shape=(6, 6, 2)),
        layers.SeparableConv2D(5, 3, padding="valid", activation="relu",
                               depth_multiplier=2, name="sep"),
        layers.BatchNormalization(name="bn"),
        layers.Flatten(name="flatten"),
        layers.Dense(3, activation="softmax", name="fc"),
    ], name="seq_bn")
    bn.compile(optimizer="sgd", loss="categorical_crossentropy")
    xtr = rng.standard_normal((16, 6, 6, 2)).astype(np.float32)
    ytr = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    bn.fit(xtr, ytr, epochs=1, verbose=0)
    x_bn = rng.standard_normal((2, 6, 6, 2)).astype(np.float32)
    preds["bn_x"] = x_bn
    preds["bn_y"] = bn.predict(x_bn, verbose=0)
    bn.save(FIXDIR / "real_bn.h5")

    # 5. Weights-only file (keras-applications distribution format)
    cnn.save_weights(FIXDIR / "real_cnn.weights.h5")

    np.savez(FIXDIR / "predictions.npz", **preds)

    manifest = {
        "generator": "tests/make_keras_fixtures.py",
        "keras_version": keras.__version__,
        "backend": keras.backend.backend(),
        "python": sys.version.split()[0],
        "files": sorted(p.name for p in FIXDIR.glob("*.h5")),
    }
    (FIXDIR / "MANIFEST.json").write_text(json.dumps(manifest, indent=2))
    print(json.dumps(manifest, indent=2))


if __name__ == "__main__":
    main()
